"""Cross-replica cache tier: the replica set as ONE cache, not N.

Everything above the balance layer used to be N independent copies of one
server — a replica death lost its KV prefix trie and response cache, and
a prompt prefix prefilled on replica A bought replica B nothing.  This
module makes cached state span the fleet:

- **prefix tier**: each replica keeps a host-side store of the KV blocks
  its prefix cache published (token-chain keyed, LRU-bounded).  A peer
  admission whose local trie misses asks the fleet
  (:meth:`FleetTier.prefix_lookup`) and installs the fetched blocks into
  its own pool, so a prefix prefilled anywhere saves prefill everywhere —
  and a parked (preempted) stream exported at planned retire resumes on a
  surviving replica from the same store;
- **response-cache tier**: a unary local cache miss consults peers
  (:meth:`FleetTier.cache_lookup`) before dispatching — a fleet-hot key
  costs the fleet one execution, not one per replica;
- **gossip**: a background round piggybacks two compact payloads on the
  peer transport — per-tenant admission counters (so token-bucket quotas
  account fleet-wide; see ``TenantQoS.absorb_remote``) and digest-prefix
  summaries (what the balance layer's prefix-aware routing policy keys
  on; see :func:`chain_digests` and ``balance/policy.py``).

Transport: the same length-prefixed JSON frames as the perf rendezvous
(:mod:`client_tpu.perf.rendezvous`), one request/response per connection
so the peer server stays stateless and a half-dead peer can only wedge
its own connection.

**The degraded-tier guarantee** — a degraded tier must never be slower
than no tier: every peer lookup is bounded by ``fan_out`` peers x a
short per-peer connect/read timeout, each peer sits behind its own
:class:`~client_tpu.resilience.CircuitBreaker` (a dead peer stops being
dialed after ``failure_threshold`` strikes and is only re-probed after
``reset_timeout_s``), and every failure path falls back to local-only.
With every peer unreachable the steady state is "breaker open, lookup
returns immediately" — the serve path never blocks on the fleet.

**Locking discipline**: peer RPCs (``cache_lookup`` / ``prefix_lookup``
/ ``gossip_now`` and anything that reaches :meth:`FleetTier._peer_call`)
MUST run with no engine or pool lock held — a peer call under the LM
engine's ``_cv`` or the balance pool's lock would stall every decode
tick / route behind a slow peer's timeout.  The tpu-lint
``PEER-CALL-UNDER-LOCK`` rule enforces this shape program-wide; this
module itself only ever touches its own ``_lock`` for host-side
bookkeeping and releases it before any socket work.
"""

import base64
import hashlib
import socket
import threading
import time
from collections import OrderedDict

import numpy as np

from client_tpu.perf.rendezvous import recv_frame, send_frame
from client_tpu.resilience import CircuitBreakerRegistry, CircuitOpenError
from client_tpu.serve.metrics import FLEET_HELP

__all__ = [
    "FleetTier",
    "chain_digests",
    "fetch_summary",
]


def chain_digests(tokens, block_size, max_blocks=None):
    """Cumulative digest per FULL token block of *tokens*.

    ``digests[i]`` identifies the first ``(i + 1) * block_size`` tokens —
    the same chain identity the prefix trie keys on, compressed to 16 hex
    chars so thousands fit in a gossip frame.  Both sides of prefix-aware
    routing use this: replicas summarize their stores with it and clients
    stamp it into ``request_ctx['prefix_digests']``.
    """
    row = [int(t) for t in np.asarray(tokens).reshape(-1)]
    block_size = int(block_size)
    n = len(row) // block_size
    if max_blocks is not None:
        n = min(n, int(max_blocks))
    digest = hashlib.sha256()
    out = []
    for i in range(n):
        block = row[i * block_size:(i + 1) * block_size]
        digest.update((",".join(map(str, block)) + ";").encode("ascii"))
        out.append(digest.hexdigest()[:16])
    return out


def _encode_block(arrays):
    """One block's per-layer [block_size, kv_heads, head_dim] arrays ->
    JSON-safe dict (dtype + shape + base64 payload per layer)."""
    return [
        {
            "dtype": str(a.dtype),
            "shape": list(a.shape),
            "data": base64.b64encode(np.ascontiguousarray(a).tobytes())
            .decode("ascii"),
        }
        for a in arrays
    ]


def _decode_block(encoded):
    return [
        np.frombuffer(
            base64.b64decode(e["data"]), dtype=np.dtype(e["dtype"])
        ).reshape(e["shape"])
        for e in encoded
    ]


class _PrefixStore:
    """Host-side store of published KV prefix blocks, token-chain keyed.

    One entry per FULL block, keyed by the flattened token prefix up to
    and including that block (exact tuple keys, like the on-device trie:
    a match is a guarantee).  Values are per-layer host arrays — no
    device state, so serving a peer's lookup touches no engine lock and
    no accelerator.  LRU-bounded by block count.
    """

    def __init__(self, max_blocks=4096):
        self.max_blocks = int(max_blocks)
        self._lock = threading.Lock()
        # tuple(tokens[: (i+1)*bs]) -> (digest, k_layers, v_layers)
        self._entries = OrderedDict()

    def put(self, row, n_blocks, block_size, host_k, host_v):
        """Insert ``n_blocks`` leading full blocks of *row* (host arrays
        per layer, shaped [>=n_blocks, block_size, kv, hd])."""
        row = [int(t) for t in np.asarray(row).reshape(-1)]
        n_blocks = min(int(n_blocks), len(row) // int(block_size))
        digests = chain_digests(row, block_size, n_blocks)
        with self._lock:
            for i in range(n_blocks):
                key = tuple(row[: (i + 1) * int(block_size)])
                if key not in self._entries:
                    self._entries[key] = (
                        digests[i],
                        [np.asarray(k[i]) for k in host_k],
                        [np.asarray(v[i]) for v in host_v],
                    )
                self._entries.move_to_end(key)
            while len(self._entries) > self.max_blocks:
                self._entries.popitem(last=False)

    def lookup(self, row, block_size, max_blocks):
        """Longest stored chain for *row*: ``(covered, k_layers,
        v_layers)`` with per-layer arrays stacked [covered, bs, kv, hd],
        or None on a total miss."""
        row = [int(t) for t in np.asarray(row).reshape(-1)]
        block_size = int(block_size)
        hits = []
        with self._lock:
            for i in range(int(max_blocks)):
                key = tuple(row[: (i + 1) * block_size])
                entry = self._entries.get(key)
                if entry is None:
                    break
                self._entries.move_to_end(key)
                hits.append(entry)
        if not hits:
            return None
        n_layers = len(hits[0][1])
        k_layers = [
            np.stack([h[1][layer] for h in hits]) for layer in range(n_layers)
        ]
        v_layers = [
            np.stack([h[2][layer] for h in hits]) for layer in range(n_layers)
        ]
        return len(hits), k_layers, v_layers

    def digests(self, limit=512):
        """Most-recently-used chain digests (the gossip summary)."""
        with self._lock:
            keys = list(self._entries)[-int(limit):]
            return [self._entries[k][0] for k in keys]

    @property
    def blocks(self):
        with self._lock:
            return len(self._entries)

    def clear(self):
        with self._lock:
            self._entries.clear()


def fetch_summary(addr, timeout_s=0.5):
    """One replica's routing summary ``{"prefix_digests": [...],
    "cache_digests": [...]}`` from its fleet peer port — the payload a
    pool health probe piggybacks (``EndpointPool.set_summary``).  Raises
    on transport failure (the probe loop treats that as no-summary)."""
    host, _, port = str(addr).rpartition(":")
    with socket.create_connection(
        (host or "127.0.0.1", int(port)), timeout=timeout_s
    ) as sock:
        sock.settimeout(timeout_s)
        send_frame(sock, {"op": "summary"})
        reply = recv_frame(sock)
    return {
        "prefix_digests": list(reply.get("prefix_digests") or ()),
        "cache_digests": list(reply.get("cache_digests") or ()),
    }


class FleetTier:
    """One replica's membership in the cross-replica cache tier.

    Owns the peer-facing server (answers ``cache_get`` / ``prefix_get``
    / ``gossip`` / ``summary`` / ``ping``), the host-side
    :class:`_PrefixStore`, the per-peer circuit breakers, and the gossip
    loop.  Attach to a serving engine with :meth:`attach` (wires the
    response cache + TenantQoS; the LM engine binds itself through the
    model binder — see ``language.lm_streaming_batched_model``).

    Peer RPC methods must be called with NO engine/pool lock held (the
    ``PEER-CALL-UNDER-LOCK`` gate); local-store methods
    (:meth:`export_prefix`, :meth:`local_summary`) are host-side only
    and safe anywhere outside device-dispatch critical sections.
    """

    def __init__(self, bind="127.0.0.1:0", peers=(), lookup_timeout_s=0.25,
                 fan_out=2, gossip_interval_s=2.0, failure_threshold=3,
                 reset_timeout_s=5.0, max_store_blocks=4096,
                 summary_limit=512, registry=None):
        host, _, port = str(bind).rpartition(":")
        self._bind_host = host or "127.0.0.1"
        self._bind_port = int(port)
        self.lookup_timeout_s = float(lookup_timeout_s)
        self.fan_out = max(int(fan_out), 1)
        self.gossip_interval_s = float(gossip_interval_s)
        self.summary_limit = int(summary_limit)
        self.registry = registry
        self.store = _PrefixStore(max_store_blocks)
        self._breakers = CircuitBreakerRegistry(
            failure_threshold=failure_threshold,
            reset_timeout_s=reset_timeout_s,
        )
        self._lock = threading.Lock()  # peers list + counters only
        self._peers = [str(p) for p in peers]
        # addr -> {tenant: n}: admission deltas not yet ACKED by that
        # peer.  delta_counts() is destructive, so a failed/breaker-open
        # send must not lose its deltas — they retry next round (a long-
        # dead peer's map stays bounded by the tenant count; its counts
        # drain into the peer's bucket, floored at zero, when it revives)
        self._pending_gossip = {}
        self._engine = None      # InferenceEngine (response cache + qos)
        self._server = None
        self._accept_thread = None
        self._gossip_thread = None
        self._stop = threading.Event()
        self._address = None
        # host-side counters (mirrored into the registry when bound)
        self.peer_hits = 0
        self.peer_misses = 0
        self.peer_errors = 0
        self.peer_skips = 0
        self.gossip_rounds = 0
        self.served = 0  # peer requests this replica answered

    # -- lifecycle ---------------------------------------------------------

    def attach(self, engine):
        """Bind to an :class:`~client_tpu.serve.model_runtime.
        InferenceEngine`: the tier reads its response cache + TenantQoS
        and the engine routes front-door misses through the tier.
        (Written under the tier lock: the peer-server and gossip threads
        may already be running when a server attaches late.)"""
        with self._lock:
            self._engine = engine
            if self.registry is None and getattr(engine, "metrics", None):
                self.registry = engine.metrics
        engine.fleet = self
        return self

    def start(self):
        if self._server is not None:
            return self
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self._bind_host, self._bind_port))
        srv.listen(16)
        srv.settimeout(0.2)
        self._server = srv
        with self._lock:  # peers() filters against it from other threads
            self._address = "%s:%d" % srv.getsockname()[:2]
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._serve_loop, args=(srv, self._stop),
            name="fleet-peer", daemon=True,
        )
        self._accept_thread.start()
        if self.gossip_interval_s > 0:
            self._gossip_thread = threading.Thread(
                target=self._gossip_loop, args=(self._stop,),
                name="fleet-gossip", daemon=True,
            )
            self._gossip_thread.start()
        return self

    def close(self):
        self._stop.set()
        for thread in (self._accept_thread, self._gossip_thread):
            if thread is not None:
                thread.join(timeout=5)
        self._accept_thread = self._gossip_thread = None
        if self._server is not None:
            self._server.close()
            self._server = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    @property
    def address(self):
        return self._address

    def set_peers(self, addrs):
        """Install the peer set.  Membership lists can be shared
        verbatim across the fleet: the replica's own address is filtered
        at USE time (:meth:`peers`), which also covers addresses handed
        to the constructor or installed before :meth:`start` bound the
        listen port — a replica gossiping to itself would double-drain
        its own tenant quotas."""
        with self._lock:
            self._peers = [str(a) for a in addrs]

    def peers(self):
        with self._lock:
            return [a for a in self._peers if a != self._address]

    # -- peer server side --------------------------------------------------

    def _serve_loop(self, srv, stop):
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # one short-lived thread per connection: a half-dead peer
            # holding a partial frame wedges only ITS handler, never the
            # accept loop — healthy peers' lookups keep answering inside
            # their timeout instead of collecting breaker strikes
            threading.Thread(
                target=self._serve_one, args=(conn,),
                name="fleet-peer-conn", daemon=True,
            ).start()

    def _serve_one(self, conn):
        try:
            conn.settimeout(max(self.lookup_timeout_s * 4, 1.0))
            request = recv_frame(conn)
            send_frame(conn, self._handle(request))
            with self._lock:
                self.served += 1
        except Exception:
            # a garbled/half-dead peer costs exactly one connection
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, request):
        op = request.get("op")
        if op == "ping":
            return {"ok": True}
        if op == "summary":
            return self.local_summary()
        if op == "cache_get":
            return self._handle_cache_get(request.get("key"))
        if op == "prefix_get":
            return self._handle_prefix_get(request)
        if op == "gossip":
            engine = self._engine
            qos = getattr(engine, "qos", None) if engine else None
            if qos is not None:
                qos.absorb_remote(request.get("tenants") or {})
            return {"ok": True}
        return {"error": f"unknown op {op!r}"}

    def _handle_cache_get(self, key):
        engine = self._engine
        cache = getattr(engine, "response_cache", None) if engine else None
        value = cache.peek(key) if cache is not None and key else None
        if value is None:
            return {"hit": False}
        response, blobs = value
        return {
            "hit": True,
            "response": response,
            "blobs": [
                base64.b64encode(bytes(b)).decode("ascii") for b in blobs
            ],
        }

    def _handle_prefix_get(self, request):
        start = max(int(request.get("start") or 0), 0)
        got = self.store.lookup(
            request.get("tokens") or [],
            int(request.get("block_size") or 0) or 1,
            int(request.get("max_blocks") or 0),
        )
        if got is None or got[0] <= start:
            # nothing beyond what the asker already holds locally
            return {"hit": False}
        covered, k_layers, v_layers = got
        return {
            "hit": True,
            "covered": covered,
            "start": start,
            # only the tail past the asker's local match travels: the
            # first `start` blocks would be sliced off and discarded,
            # and base64-inflated KV is the expensive part of the frame
            "k": _encode_block([k[start:] for k in k_layers]),
            "v": _encode_block([v[start:] for v in v_layers]),
        }

    # -- peer client side (NEVER call with an engine/pool lock held) -------

    def _peer_call(self, addr, payload):
        """One framed request/response against *addr* with bounded
        connect + read timeouts.  Raises OSError-family on any transport
        failure — callers feed the per-peer breaker."""
        host, _, port = addr.rpartition(":")
        with socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=self.lookup_timeout_s
        ) as sock:
            sock.settimeout(self.lookup_timeout_s)
            send_frame(sock, payload)
            return recv_frame(sock)

    def _candidates(self):
        """Breaker-admitted peer snapshot (skips counted): at most
        ``fan_out`` peers per lookup, so a lookup's worst case is
        ``fan_out * lookup_timeout_s`` even before breakers open."""
        out = []
        for addr in self.peers():
            breaker = self._breakers.get(addr)
            try:
                breaker.before_attempt()
            except CircuitOpenError:
                with self._lock:
                    self.peer_skips += 1
                self._count("ctpu_fleet_peer_skips_total")
                continue
            out.append((addr, breaker))
            if len(out) >= self.fan_out:
                break
        return out

    def _ask(self, payload):
        """Fan the payload out peer-by-peer.  Yields ``(addr, reply)``
        for each answered peer; ANY peer failure is a breaker strike and
        a local-only fallback, never a caller-visible error."""
        for addr, breaker in self._candidates():
            try:
                reply = self._peer_call(addr, payload)
            except Exception:  # noqa: BLE001 - containment is the point
                breaker.record_failure()
                with self._lock:
                    self.peer_errors += 1
                self._count("ctpu_fleet_peer_errors_total")
                continue
            breaker.record_success()
            yield addr, reply

    def cache_lookup(self, key):
        """Peer response-cache lookup: ``(response_json, blobs)`` or
        None.  Bounded fan-out, per-peer timeout, local-only on error."""
        for _addr, reply in self._ask({"op": "cache_get", "key": key}):
            if reply.get("hit"):
                self._note_lookup(True, "cache")
                blobs = [
                    base64.b64decode(b) for b in reply.get("blobs") or ()
                ]
                return reply["response"], blobs
        self._note_lookup(False, "cache")
        return None

    def prefix_lookup(self, tokens, block_size, max_blocks,
                      start_blocks=0):
        """Longest peer-cached KV chain for *tokens*: ``(covered,
        k_layers, v_layers, start)`` or None.  ``start_blocks`` is how
        many leading blocks the asker already holds locally — only the
        tail past it travels the wire; the returned per-layer host
        arrays cover blocks ``[start, covered)``.  Takes the best answer
        across the fan-out; stops early on full coverage."""
        tokens = [int(t) for t in np.asarray(tokens).reshape(-1)]
        start_blocks = max(int(start_blocks), 0)
        payload = {
            "op": "prefix_get",
            "tokens": tokens,
            "block_size": int(block_size),
            "max_blocks": int(max_blocks),
            "start": start_blocks,
        }
        best = None
        for _addr, reply in self._ask(payload):
            if not reply.get("hit"):
                continue
            covered = int(reply.get("covered") or 0)
            if best is None or covered > best[0]:
                try:
                    best = (
                        covered,
                        _decode_block(reply["k"]),
                        _decode_block(reply["v"]),
                        start_blocks,
                    )
                except (KeyError, ValueError):
                    continue  # malformed peer payload: ignore it
                if covered >= int(max_blocks):
                    break
        self._note_lookup(best is not None, "prefix")
        return best

    def gossip_now(self):
        """Push one gossip round to EVERY breaker-admitted peer: the
        local per-tenant admission deltas (fleet-wide quota accounting).
        Deltas a peer did not ACK — send failure, open breaker — are
        retained per-peer and retried next round, so a transient
        partition delays convergence instead of losing admissions.
        Returns the number of peers that acked."""
        engine = self._engine
        qos = getattr(engine, "qos", None) if engine else None
        fresh = qos.delta_counts() if qos is not None else {}
        peers = self.peers()
        with self._lock:
            for addr in peers:
                pending = self._pending_gossip.setdefault(addr, {})
                for tenant, n in fresh.items():
                    pending[tenant] = pending.get(tenant, 0) + n
            for addr in list(self._pending_gossip):
                if addr not in peers:  # departed peer: drop its backlog
                    del self._pending_gossip[addr]
        acked = 0
        for addr in peers:
            with self._lock:
                tenants = dict(self._pending_gossip.get(addr) or {})
            breaker = self._breakers.get(addr)
            try:
                breaker.before_attempt()
            except CircuitOpenError:
                continue
            try:
                self._peer_call(addr, {"op": "gossip", "tenants": tenants})
            except Exception:  # noqa: BLE001 - containment is the point
                breaker.record_failure()
                continue
            breaker.record_success()
            acked += 1
            with self._lock:
                pending = self._pending_gossip.get(addr)
                if pending is not None:
                    # subtract what was ACKED (concurrent rounds may have
                    # grown the backlog since the snapshot)
                    for tenant, n in tenants.items():
                        left = pending.get(tenant, 0) - n
                        if left > 0:
                            pending[tenant] = left
                        else:
                            pending.pop(tenant, None)
        with self._lock:
            self.gossip_rounds += 1
        self._count("ctpu_fleet_gossip_rounds_total")
        return acked

    def _gossip_loop(self, stop):
        while not stop.wait(self.gossip_interval_s):
            try:
                self.gossip_now()
            except Exception:  # pragma: no cover - defensive
                pass

    # -- local store (host-side; no peer RPC, no device state) -------------

    def export_prefix(self, row, n_blocks, block_size, host_k, host_v):
        """Install *n_blocks* leading full blocks of the token row into
        this replica's host store (the LM engine calls this at prefill
        completion and at planned retire for parked streams — always
        OUTSIDE its condition lock; the arrays are already host-side)."""
        self.store.put(row, n_blocks, block_size, host_k, host_v)
        self._gauge()

    def local_summary(self):
        """The gossip/probe summary: most-recent chain digests plus the
        response cache's digest keys (truncated to the summary limit)."""
        engine = self._engine
        cache = getattr(engine, "response_cache", None) if engine else None
        cache_digests = (
            cache.keys()[-self.summary_limit:] if cache is not None else []
        )
        return {
            "prefix_digests": self.store.digests(self.summary_limit),
            "cache_digests": cache_digests,
        }

    # -- metrics / introspection -------------------------------------------

    def _count(self, name, labels=None, value=1):
        if self.registry is not None:
            self.registry.inc(name, labels, value=value,
                              help_=FLEET_HELP[name])

    def _gauge(self):
        if self.registry is not None:
            self.registry.set(
                "ctpu_fleet_store_blocks", None, self.store.blocks,
                help_=FLEET_HELP["ctpu_fleet_store_blocks"],
            )

    def _note_lookup(self, hit, op):
        with self._lock:
            if hit:
                self.peer_hits += 1
            else:
                self.peer_misses += 1
        self._count(
            "ctpu_fleet_peer_hits_total" if hit
            else "ctpu_fleet_peer_misses_total",
            {"op": op},
        )

    def stats(self):
        store_blocks = self.store.blocks
        with self._lock:
            return {
                "peer_hits": self.peer_hits,
                "peer_misses": self.peer_misses,
                "peer_errors": self.peer_errors,
                "peer_skips": self.peer_skips,
                "gossip_rounds": self.gossip_rounds,
                "served": self.served,
                "store_blocks": store_blocks,
                "peers": list(self._peers),
            }
