"""Cross-replica cache tier: the replica set as ONE cache, not N.

Everything above the balance layer used to be N independent copies of one
server — a replica death lost its KV prefix trie and response cache, and
a prompt prefix prefilled on replica A bought replica B nothing.  This
module makes cached state span the fleet:

- **prefix tier**: each replica keeps a host-side store of the KV blocks
  its prefix cache published (token-chain keyed, LRU-bounded).  A peer
  admission whose local trie misses asks the fleet
  (:meth:`FleetTier.prefix_lookup`) and installs the fetched blocks into
  its own pool, so a prefix prefilled anywhere saves prefill everywhere —
  and a parked (preempted) stream exported at planned retire resumes on a
  surviving replica from the same store;
- **response-cache tier**: a unary local cache miss consults peers
  (:meth:`FleetTier.cache_lookup`) before dispatching — a fleet-hot key
  costs the fleet one execution, not one per replica;
- **gossip**: a background round piggybacks two compact payloads on the
  peer transport — per-tenant admission counters (so token-bucket quotas
  account fleet-wide; see ``TenantQoS.absorb_remote``) and digest-prefix
  summaries (what the balance layer's prefix-aware routing policy keys
  on; see :func:`chain_digests` and ``balance/policy.py``).

Transport: the same length-prefixed JSON frames as the perf rendezvous
(:mod:`client_tpu.perf.rendezvous`), one request/response per connection
so the peer server stays stateless and a half-dead peer can only wedge
its own connection.

**The degraded-tier guarantee** — a degraded tier must never be slower
than no tier: every peer lookup is bounded by ``fan_out`` peers x a
short per-peer connect/read timeout, each peer sits behind its own
:class:`~client_tpu.resilience.CircuitBreaker` (a dead peer stops being
dialed after ``failure_threshold`` strikes and is only re-probed after
``reset_timeout_s``), and every failure path falls back to local-only.
With every peer unreachable the steady state is "breaker open, lookup
returns immediately" — the serve path never blocks on the fleet.

**Locking discipline**: peer RPCs (``cache_lookup`` / ``prefix_lookup``
/ ``gossip_now`` and anything that reaches :meth:`FleetTier._peer_call`)
MUST run with no engine or pool lock held — a peer call under the LM
engine's ``_cv`` or the balance pool's lock would stall every decode
tick / route behind a slow peer's timeout.  The tpu-lint
``PEER-CALL-UNDER-LOCK`` rule enforces this shape program-wide; this
module itself only ever touches its own ``_lock`` for host-side
bookkeeping and releases it before any socket work.
"""

import base64
import hashlib
import json
import queue
import socket
import threading
import time
from collections import OrderedDict

import numpy as np

from client_tpu.analysis.witness import witness_shared
from client_tpu.perf.rendezvous import recv_frame, send_frame
from client_tpu.resilience import CircuitBreakerRegistry, CircuitOpenError
from client_tpu.serve.metrics import FLEET_HELP

__all__ = [
    "FleetTier",
    "chain_digests",
    "fetch_summary",
]


def _frame_bytes(payload):
    """Approximate payload size of one fleet frame: the base64 KV/blob
    fields dominate every heavy op, so summing their lengths (plus the
    snapshot's encoded values) is within a few percent of the wire size
    at none of json.dumps' cost.  Only computed for TRACED calls."""
    n = 0
    for key in ("k", "v"):
        for e in payload.get(key) or ():
            if isinstance(e, dict):
                n += len(e.get("data") or "")
    for b in payload.get("blobs") or ():
        n += len(b)
    n += 4 * len(payload.get("tokens") or ())
    snapshot = payload.get("snapshot")
    if isinstance(snapshot, dict):
        for value in snapshot.values():
            if isinstance(value, str):
                n += len(value)
            elif isinstance(value, dict):
                n += sum(
                    len(v) for v in value.values() if isinstance(v, str)
                )
    return n


def chain_digests(tokens, block_size, max_blocks=None):
    """Cumulative digest per FULL token block of *tokens*.

    ``digests[i]`` identifies the first ``(i + 1) * block_size`` tokens —
    the same chain identity the prefix trie keys on, compressed to 16 hex
    chars so thousands fit in a gossip frame.  Both sides of prefix-aware
    routing use this: replicas summarize their stores with it and clients
    stamp it into ``request_ctx['prefix_digests']``.
    """
    row = [int(t) for t in np.asarray(tokens).reshape(-1)]
    block_size = int(block_size)
    n = len(row) // block_size
    if max_blocks is not None:
        n = min(n, int(max_blocks))
    digest = hashlib.sha256()
    out = []
    for i in range(n):
        block = row[i * block_size:(i + 1) * block_size]
        digest.update((",".join(map(str, block)) + ";").encode("ascii"))
        out.append(digest.hexdigest()[:16])
    return out


def _encode_block(arrays):
    """One block's per-layer [block_size, kv_heads, head_dim] arrays ->
    JSON-safe dict (dtype + shape + base64 payload per layer)."""
    return [
        {
            "dtype": str(a.dtype),
            "shape": list(a.shape),
            "data": base64.b64encode(np.ascontiguousarray(a).tobytes())
            .decode("ascii"),
        }
        for a in arrays
    ]


def _decode_block(encoded):
    return [
        np.frombuffer(
            base64.b64decode(e["data"]), dtype=np.dtype(e["dtype"])
        ).reshape(e["shape"])
        for e in encoded
    ]


class _PrefixStore:
    """Host-side store of published KV prefix blocks, token-chain keyed.

    One entry per FULL block, keyed by the flattened token prefix up to
    and including that block (exact tuple keys, like the on-device trie:
    a match is a guarantee).  Values are per-layer host arrays — no
    device state, so serving a peer's lookup touches no engine lock and
    no accelerator.  LRU-bounded by block count.
    """

    def __init__(self, max_blocks=4096):
        self.max_blocks = int(max_blocks)
        self._lock = threading.Lock()
        # tuple(tokens[: (i+1)*bs]) ->
        #     [digest, k_layers, v_layers, hits, pushed]
        # hits counts demand (local re-publishes + peer lookups) — the
        # anti-entropy loop pushes chains past the hot threshold; pushed
        # marks chains already replicated (cleared on push failure so a
        # later hit re-queues them)
        self._entries = OrderedDict()
        self.block_size = None  # last-seen block size (uniform per engine)

    def put(self, row, n_blocks, block_size, host_k, host_v):
        """Insert ``n_blocks`` leading full blocks of *row* (host arrays
        per layer, shaped [>=n_blocks, block_size, kv, hd])."""
        row = [int(t) for t in np.asarray(row).reshape(-1)]
        n_blocks = min(int(n_blocks), len(row) // int(block_size))
        digests = chain_digests(row, block_size, n_blocks)
        with self._lock:
            self.block_size = int(block_size)
            for i in range(n_blocks):
                key = tuple(row[: (i + 1) * int(block_size)])
                entry = self._entries.get(key)
                if entry is None:
                    self._entries[key] = [
                        digests[i],
                        [np.asarray(k[i]) for k in host_k],
                        [np.asarray(v[i]) for v in host_v],
                        0,
                        False,
                    ]
                else:
                    entry[3] += 1  # re-published: local demand
                self._entries.move_to_end(key)
            while len(self._entries) > self.max_blocks:
                self._entries.popitem(last=False)

    def lookup(self, row, block_size, max_blocks, count_hits=True):
        """Longest stored chain for *row*: ``(covered, k_layers,
        v_layers)`` with per-layer arrays stacked [covered, bs, kv, hd],
        or None on a total miss."""
        row = [int(t) for t in np.asarray(row).reshape(-1)]
        block_size = int(block_size)
        hits = []
        with self._lock:
            for i in range(int(max_blocks)):
                key = tuple(row[: (i + 1) * block_size])
                entry = self._entries.get(key)
                if entry is None:
                    break
                self._entries.move_to_end(key)
                if count_hits:
                    entry[3] += 1
                hits.append(entry)
        if not hits:
            return None
        n_layers = len(hits[0][1])
        k_layers = [
            np.stack([h[1][layer] for h in hits]) for layer in range(n_layers)
        ]
        v_layers = [
            np.stack([h[2][layer] for h in hits]) for layer in range(n_layers)
        ]
        return len(hits), k_layers, v_layers

    def digests(self, limit=512):
        """Most-recently-used chain digests (the gossip summary)."""
        with self._lock:
            keys = list(self._entries)[-int(limit):]
            return [self._entries[k][0] for k in keys]

    def hot_count(self, threshold):
        """Chains at or past the hot-hit threshold (the prefix-affinity
        pressure signal gossiped on probes)."""
        with self._lock:
            return sum(
                1 for e in self._entries.values() if e[3] >= threshold
            )

    def take_hot(self, threshold):
        """Hot, not-yet-replicated chain heads: ``[(row, n_blocks)]``.

        Longest-chain-first with proper prefixes of an already-taken
        chain skipped (one ``prefix_put`` of the longest chain carries
        every sub-chain), each marked pushed so it is taken once; a
        failed push clears the mark via :meth:`unmark_pushed`."""
        with self._lock:
            if self.block_size is None:
                return []
            hot = sorted(
                (
                    key for key, e in self._entries.items()
                    if e[3] >= threshold and not e[4]
                ),
                key=len, reverse=True,
            )
            taken = []
            for key in hot:
                covered = False
                for longer, _n in taken:
                    if tuple(longer[: len(key)]) == key:
                        covered = True
                        break
                self._entries[key][4] = True
                if not covered:
                    taken.append((list(key), len(key) // self.block_size))
            return taken

    def unmark_pushed(self, row):
        """Clear the replicated mark on the chain AND every sub-chain
        after a failed push: take_hot marked the covered prefixes pushed
        too (one prefix_put of the longest chain carries them), so a
        failed push must re-arm the whole family or an eviction of the
        head chain would leave still-hot sub-chains skipped forever."""
        row = [int(t) for t in row]
        with self._lock:
            block_size = self.block_size or len(row) or 1
            for i in range(len(row) // block_size):
                entry = self._entries.get(tuple(row[: (i + 1) * block_size]))
                if entry is not None:
                    entry[4] = False

    @property
    def blocks(self):
        with self._lock:
            return len(self._entries)

    def clear(self):
        with self._lock:
            self._entries.clear()


def _seq_version(snapshot):
    """Snapshot ordering key: ``(epoch, step)`` — the incarnation stamp
    first, so a restarted sequence id's fresh epoch beats the dead
    incarnation's higher step count."""
    return (
        float(snapshot.get("epoch", 0.0)), int(snapshot.get("step", 0))
    )


@witness_shared("_lock")
class _SequenceStore:
    """Replicated sequence-state snapshots, versioned by (epoch, step).

    One snapshot per sequence id (``SequenceContext.export()`` shape).
    ``put`` is monotonic: a snapshot whose ``(epoch, step)`` version
    does not beat the stored one is STALE and rejected — replication,
    retries, and gossip races can never move a sequence backwards, and
    a RESTARTED sequence id (fresh epoch) overwrites the previous
    incarnation's leftovers.  LRU-bounded; entries idle past ``ttl_s``
    expire at read time (mirroring the engine's own
    ``max_sequence_idle_s`` hygiene)."""

    def __init__(self, max_sequences=4096, ttl_s=120.0):
        self.max_sequences = int(max_sequences)
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._entries = OrderedDict()  # seq_id -> (snapshot, stored_at)
        self.stale_rejected = 0

    def put(self, snapshot):
        """Install one snapshot; False when stale (version not newer)."""
        seq_id = snapshot.get("sequence_id")
        if seq_id is None:
            return False
        with self._lock:
            old = self._entries.get(seq_id)
            if old is not None and _seq_version(old[0]) >= _seq_version(
                snapshot
            ):
                self.stale_rejected += 1
                return False
            self._entries[seq_id] = (snapshot, time.monotonic())
            self._entries.move_to_end(seq_id)
            while len(self._entries) > self.max_sequences:
                self._entries.popitem(last=False)
            return True

    def get(self, seq_id):
        with self._lock:
            entry = self._entries.get(seq_id)
            if entry is None:
                return None
            if time.monotonic() - entry[1] > self.ttl_s:
                self._entries.pop(seq_id, None)
                return None
            return entry[0]

    def pop(self, seq_id):
        with self._lock:
            self._entries.pop(seq_id, None)

    @property
    def count(self):
        with self._lock:
            return len(self._entries)


def fetch_summary(addr, timeout_s=0.5):
    """One replica's routing summary ``{"prefix_digests": [...],
    "cache_digests": [...]}`` from its fleet peer port — the payload a
    pool health probe piggybacks (``EndpointPool.set_summary``).  Raises
    on transport failure (the probe loop treats that as no-summary)."""
    host, _, port = str(addr).rpartition(":")
    with socket.create_connection(
        (host or "127.0.0.1", int(port)), timeout=timeout_s
    ) as sock:
        sock.settimeout(timeout_s)
        send_frame(sock, {"op": "summary"})
        reply = recv_frame(sock)
    return {
        "prefix_digests": list(reply.get("prefix_digests") or ()),
        "cache_digests": list(reply.get("cache_digests") or ()),
        "pressure": dict(reply.get("pressure") or {}),
    }


class FleetTier:
    """One replica's membership in the cross-replica cache tier.

    Owns the peer-facing server (answers ``cache_get`` / ``prefix_get``
    / ``gossip`` / ``summary`` / ``ping``), the host-side
    :class:`_PrefixStore`, the per-peer circuit breakers, and the gossip
    loop.  Attach to a serving engine with :meth:`attach` (wires the
    response cache + TenantQoS; the LM engine binds itself through the
    model binder — see ``language.lm_streaming_batched_model``).

    Peer RPC methods must be called with NO engine/pool lock held (the
    ``PEER-CALL-UNDER-LOCK`` gate); local-store methods
    (:meth:`export_prefix`, :meth:`local_summary`) are host-side only
    and safe anywhere outside device-dispatch critical sections.
    """

    def __init__(self, bind="127.0.0.1:0", peers=(), lookup_timeout_s=0.25,
                 fan_out=2, gossip_interval_s=2.0, failure_threshold=3,
                 reset_timeout_s=5.0, max_store_blocks=4096,
                 summary_limit=512, registry=None, replicate_k=1,
                 replicate_budget_bytes_s=4 << 20, hot_hits=3,
                 replicate_interval_s=0.2, max_sequences=4096,
                 seq_ttl_s=120.0, quorum="any"):
        if quorum not in ("any", "majority"):
            raise ValueError(
                f"quorum must be 'any' or 'majority', got {quorum!r}"
            )
        host, _, port = str(bind).rpartition(":")
        self._bind_host = host or "127.0.0.1"
        self._bind_port = int(port)
        self.lookup_timeout_s = float(lookup_timeout_s)
        self.fan_out = max(int(fan_out), 1)
        self.gossip_interval_s = float(gossip_interval_s)
        self.summary_limit = int(summary_limit)
        self.registry = registry
        self.store = _PrefixStore(max_store_blocks)
        # replicated sequence-state lane (snapshots peers pushed to us,
        # plus lookups cached from peers) — the failure-domain half
        self.seq_store = _SequenceStore(max_sequences, ttl_s=seq_ttl_s)
        # proactive replication / anti-entropy: hot content pushes to K
        # peers on a bounded byte/sec budget, strictly OFF the request
        # path (a dedicated thread drains the queue)
        self.replicate_k = max(int(replicate_k), 0)
        # write-quorum mode for the durable sequence lane: "any" is the
        # historical best-effort ack (any peer count, including zero),
        # "majority" requires ceil((K+1)/2) peers to report `stored`
        # before a durable step acks to the client
        self.quorum = quorum
        self.hot_hits = max(int(hot_hits), 1)
        self.replicate_interval_s = float(replicate_interval_s)
        self._repl_rate = float(replicate_budget_bytes_s)
        self._repl_tokens = self._repl_rate
        self._repl_stamp = time.monotonic()
        self._repl_queue = queue.Queue()
        self._repl_thread = None
        # response-cache hot tracking: key -> local hit count since the
        # last push (bounded; a pushed key re-queues only on new demand)
        self._cache_hot = OrderedDict()
        self._cache_pushed = set()
        self.replicated_items = 0
        self.replicated_bytes = 0
        self.seq_pushes = 0
        self._breakers = CircuitBreakerRegistry(
            failure_threshold=failure_threshold,
            reset_timeout_s=reset_timeout_s,
        )
        self._lock = threading.Lock()  # peers list + counters only
        self._peers = [str(p) for p in peers]
        # addr -> {tenant: n}: admission deltas not yet ACKED by that
        # peer.  delta_counts() is destructive, so a failed/breaker-open
        # send must not lose its deltas — they retry next round (a long-
        # dead peer's map stays bounded by the tenant count; its counts
        # drain into the peer's bucket, floored at zero, when it revives)
        self._pending_gossip = {}
        self._engine = None      # InferenceEngine (response cache + qos)
        self._server = None
        self._accept_thread = None
        self._gossip_thread = None
        self._stop = threading.Event()
        self._address = None
        # host-side counters (mirrored into the registry when bound)
        self.peer_hits = 0
        self.peer_misses = 0
        self.peer_errors = 0
        self.peer_skips = 0
        self.gossip_rounds = 0
        self.served = 0  # peer requests this replica answered
        self.seq_quorum_acks = 0
        self.seq_quorum_refusals = 0
        # chaos seam: when set, a predicate addr -> bool consulted before
        # every outbound peer connection; False = partitioned (the
        # connection fails as if the network dropped it, so the per-peer
        # breakers accumulate real evidence).  Installed/cleared by the
        # chaos harness's partition/heal fault kinds.
        self._transport_filter = None

    # -- lifecycle ---------------------------------------------------------

    def attach(self, engine):
        """Bind to an :class:`~client_tpu.serve.model_runtime.
        InferenceEngine`: the tier reads its response cache + TenantQoS
        and the engine routes front-door misses through the tier.
        (Written under the tier lock: the peer-server and gossip threads
        may already be running when a server attaches late.)"""
        with self._lock:
            self._engine = engine
            if self.registry is None and getattr(engine, "metrics", None):
                self.registry = engine.metrics
        engine.fleet = self
        return self

    def start(self):
        if self._server is not None:
            return self
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self._bind_host, self._bind_port))
        srv.listen(16)
        srv.settimeout(0.2)
        self._server = srv
        with self._lock:  # peers() filters against it from other threads
            self._address = "%s:%d" % srv.getsockname()[:2]
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._serve_loop, args=(srv, self._stop),
            name="fleet-peer", daemon=True,
        )
        self._accept_thread.start()
        if self.gossip_interval_s > 0:
            self._gossip_thread = threading.Thread(
                target=self._gossip_loop, args=(self._stop,),
                name="fleet-gossip", daemon=True,
            )
            self._gossip_thread.start()
        if self.replicate_k > 0:
            self._repl_thread = threading.Thread(
                target=self._replicate_loop, args=(self._stop,),
                name="fleet-replicate", daemon=True,
            )
            self._repl_thread.start()
        return self

    def close(self):
        self._stop.set()
        threads = (self._accept_thread, self._gossip_thread,
                   self._repl_thread)
        for thread in threads:
            if thread is not None:
                thread.join(timeout=5)
        self._accept_thread = self._gossip_thread = None
        self._repl_thread = None
        if self._server is not None:
            self._server.close()
            self._server = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    @property
    def address(self):
        return self._address

    def set_peers(self, addrs):
        """Install the peer set.  Membership lists can be shared
        verbatim across the fleet: the replica's own address is filtered
        at USE time (:meth:`peers`), which also covers addresses handed
        to the constructor or installed before :meth:`start` bound the
        listen port — a replica gossiping to itself would double-drain
        its own tenant quotas."""
        with self._lock:
            self._peers = [str(a) for a in addrs]

    def peers(self):
        with self._lock:
            return [a for a in self._peers if a != self._address]

    # -- peer server side --------------------------------------------------

    def _serve_loop(self, srv, stop):
        # the whole pass sits under one guard (the BG-THREAD-CRASH shape):
        # an accept-loop thread that dies silently takes the peer server —
        # and every survivor's lookups against it — down with it
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
                # one short-lived thread per connection: a half-dead peer
                # holding a partial frame wedges only ITS handler, never
                # the accept loop — healthy peers' lookups keep answering
                # inside their timeout instead of collecting breaker
                # strikes
                threading.Thread(
                    target=self._serve_one, args=(conn,),
                    name="fleet-peer-conn", daemon=True,
                ).start()
            except socket.timeout:
                continue
            except OSError:
                return
            except Exception:  # thread-spawn failure: drop the connection
                try:
                    conn.close()
                except OSError:
                    pass

    def _serve_one(self, conn):
        try:
            conn.settimeout(max(self.lookup_timeout_s * 4, 1.0))
            request = recv_frame(conn)
            send_frame(conn, self._handle_traced(request))
            with self._lock:
                self.served += 1
        except Exception:
            # a garbled/half-dead peer costs exactly one connection
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _tracer(self):
        """The attached engine's Tracer (or None) — fleet spans land in
        the same store/trace file as the replica's request spans."""
        engine = self._engine
        return getattr(engine, "tracer", None) if engine else None

    def _handle_traced(self, request):
        """Serve one peer frame, recording the peer-server span under the
        CALLING replica's trace id when the frame carried a traceparent —
        a cross-replica fetch then reads as one trace spanning both
        processes (the other half is the caller's peer_span)."""
        tracer = self._tracer()
        traceparent = request.get("traceparent")
        if tracer is None or not traceparent:
            return self._handle(request)
        op = str(request.get("op") or "?")
        with tracer.serve_span(op, traceparent=traceparent) as span:
            reply = self._handle(request)
            if span is not None:
                for key in ("hit", "stored", "ok"):
                    if key in reply:
                        span.tags[key] = bool(reply[key])
                span.tags["bytes"] = _frame_bytes(reply) or _frame_bytes(
                    request
                )
        return reply

    def _handle(self, request):
        op = request.get("op")
        if op == "ping":
            return {"ok": True}
        if op == "summary":
            return self.local_summary()
        if op == "cache_get":
            return self._handle_cache_get(request.get("key"))
        if op == "prefix_get":
            return self._handle_prefix_get(request)
        if op == "prefix_put":
            return self._handle_prefix_put(request)
        if op == "cache_put":
            return self._handle_cache_put(request)
        if op == "seq_put":
            return self._handle_seq_put(request)
        if op == "seq_get":
            return self._handle_seq_get(request.get("sequence_id"))
        if op == "gossip":
            engine = self._engine
            qos = getattr(engine, "qos", None) if engine else None
            if qos is not None:
                qos.absorb_remote(request.get("tenants") or {})
            return {"ok": True}
        return {"error": f"unknown op {op!r}"}

    def _handle_cache_get(self, key):
        engine = self._engine
        cache = getattr(engine, "response_cache", None) if engine else None
        value = cache.peek(key) if cache is not None and key else None
        if value is None:
            return {"hit": False}
        response, blobs = value
        return {
            "hit": True,
            "response": response,
            "blobs": [
                base64.b64encode(bytes(b)).decode("ascii") for b in blobs
            ],
        }

    def _handle_prefix_get(self, request):
        start = max(int(request.get("start") or 0), 0)
        got = self.store.lookup(
            request.get("tokens") or [],
            int(request.get("block_size") or 0) or 1,
            int(request.get("max_blocks") or 0),
        )
        if got is None or got[0] <= start:
            # nothing beyond what the asker already holds locally
            return {"hit": False}
        covered, k_layers, v_layers = got
        return {
            "hit": True,
            "covered": covered,
            "start": start,
            # only the tail past the asker's local match travels: the
            # first `start` blocks would be sliced off and discarded,
            # and base64-inflated KV is the expensive part of the frame
            "k": _encode_block([k[start:] for k in k_layers]),
            "v": _encode_block([v[start:] for v in v_layers]),
        }

    def _handle_prefix_put(self, request):
        """Anti-entropy receive: install a peer's pushed KV chain into
        this replica's host store (host-side only; no device state)."""
        try:
            self.store.put(
                request.get("tokens") or [],
                int(request.get("n_blocks") or 0),
                int(request.get("block_size") or 0) or 1,
                _decode_block(request.get("k") or []),
                _decode_block(request.get("v") or []),
            )
        except (KeyError, ValueError):
            return {"ok": False}
        self._gauge()
        return {"ok": True}

    def _handle_cache_put(self, request):
        """Anti-entropy receive: fill a peer's pushed hot response into
        the local response cache (plain LRU insert — a remote fill
        competes for space like any local one)."""
        engine = self._engine
        cache = getattr(engine, "response_cache", None) if engine else None
        key = request.get("key")
        if cache is None or not key:
            return {"ok": False}
        blobs = [base64.b64decode(b) for b in request.get("blobs") or ()]
        cache.put(key, request.get("response") or {}, blobs)
        return {"ok": True}

    def _handle_seq_put(self, request):
        """Sequence-state lane receive: install (or, for an ended
        sequence, drop) one versioned snapshot.  Stale snapshots — step
        not beating the stored one — are rejected, never applied."""
        if request.get("ended"):
            self.seq_store.pop(request.get("sequence_id"))
            return {"ok": True, "stored": False}
        snapshot = request.get("snapshot") or {}
        stored = self.seq_store.put(snapshot)
        if not stored:
            self._count("ctpu_fleet_seq_stale_total")
        return {"ok": True, "stored": stored}

    def _handle_seq_get(self, seq_id):
        """Serve one sequence snapshot: the freshest of the replicated
        store and the attached engine's LIVE sequence (planned handoffs
        can pull state that was never pushed)."""
        if seq_id is None:
            return {"hit": False}
        snapshot = self.seq_store.get(seq_id)
        engine = self._engine
        export = getattr(engine, "export_sequence", None) if engine else None
        if export is not None:
            try:
                live = export(seq_id)
            except Exception:  # pragma: no cover - defensive
                live = None
            if live is not None and (
                snapshot is None
                or _seq_version(live) > _seq_version(snapshot)
            ):
                snapshot = live
        if snapshot is None:
            return {"hit": False}
        return {"hit": True, "snapshot": snapshot}

    # -- peer client side (NEVER call with an engine/pool lock held) -------

    def set_transport_filter(self, fn):
        """Install (or clear, with None) the chaos transport filter: a
        predicate ``addr -> bool`` consulted before every outbound peer
        connection.  ``False`` makes the call fail with OSError exactly
        where a severed network would — downstream breaker/quorum
        behavior is the real code path, not a mock."""
        with self._lock:
            self._transport_filter = fn

    def _peer_call(self, addr, payload):
        """One framed request/response against *addr* with bounded
        connect + read timeouts.  Raises OSError-family on any transport
        failure — callers feed the per-peer breaker."""
        with self._lock:  # released before any transport work
            filt = self._transport_filter
        if filt is not None and not filt(addr):
            raise OSError(f"partitioned from peer {addr}")
        host, _, port = addr.rpartition(":")
        with socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=self.lookup_timeout_s
        ) as sock:
            sock.settimeout(self.lookup_timeout_s)
            send_frame(sock, payload)
            return recv_frame(sock)

    def _traced_peer_call(self, addr, payload, breaker=None):
        """One framed peer RPC recorded as a trace span: a request-thread
        call (prefix/cache/seq lookup, the synchronous durability push)
        becomes a CHILD span under the active request trace, an
        anti-entropy-thread call a standalone subsampled span.  The
        traceparent rides the frame so the peer's serve span joins the
        same trace.  Raises exactly like :meth:`_peer_call`; tracing off
        (or unsampled) adds two attribute reads and nothing else."""
        tracer = self._tracer()
        if tracer is None:
            return self._peer_call(addr, payload)
        op = str(payload.get("op") or "?")
        with tracer.peer_span(
            op, peer=addr,
            breaker=(breaker.state if breaker is not None else ""),
        ) as span:
            if span is None:
                return self._peer_call(addr, payload)
            framed = dict(payload)
            framed["traceparent"] = span.traceparent()
            sent = _frame_bytes(payload)
            reply = self._peer_call(addr, framed)
            for key in ("hit", "stored", "ok"):
                if key in reply:
                    span.tags[key] = bool(reply[key])
            span.tags["bytes"] = sent + _frame_bytes(reply)
            return reply

    def _candidates(self, limit=None, exclude=()):
        """Breaker-admitted peer snapshot (skips counted): at most
        ``limit`` (default ``fan_out``) peers per call, so a lookup's
        worst case is ``fan_out * lookup_timeout_s`` even before
        breakers open.  ``exclude`` skips peers a caller already tried
        this round (the quorum push's widening waves)."""
        limit = self.fan_out if limit is None else int(limit)
        out = []
        for addr in self.peers():
            if addr in exclude:
                continue
            breaker = self._breakers.get(addr)
            try:
                breaker.before_attempt()
            except CircuitOpenError:
                with self._lock:
                    self.peer_skips += 1
                self._count("ctpu_fleet_peer_skips_total")
                continue
            out.append((addr, breaker))
            if len(out) >= limit:
                break
        return out

    def _ask(self, payload):
        """Fan the payload out peer-by-peer.  Yields ``(addr, reply)``
        for each answered peer; ANY peer failure is a breaker strike and
        a local-only fallback, never a caller-visible error."""
        for addr, breaker in self._candidates():
            try:
                reply = self._traced_peer_call(addr, payload, breaker)
            except Exception:  # noqa: BLE001 - containment is the point
                breaker.record_failure()
                with self._lock:
                    self.peer_errors += 1
                self._count("ctpu_fleet_peer_errors_total")
                continue
            breaker.record_success()
            yield addr, reply

    def cache_lookup(self, key):
        """Peer response-cache lookup: ``(response_json, blobs)`` or
        None.  Bounded fan-out, per-peer timeout, local-only on error."""
        for _addr, reply in self._ask({"op": "cache_get", "key": key}):
            if reply.get("hit"):
                self._note_lookup(True, "cache")
                blobs = [
                    base64.b64decode(b) for b in reply.get("blobs") or ()
                ]
                return reply["response"], blobs
        self._note_lookup(False, "cache")
        return None

    def prefix_lookup(self, tokens, block_size, max_blocks,
                      start_blocks=0):
        """Longest peer-cached KV chain for *tokens*: ``(covered,
        k_layers, v_layers, start)`` or None.  ``start_blocks`` is how
        many leading blocks the asker already holds locally — only the
        tail past it travels the wire; the returned per-layer host
        arrays cover blocks ``[start, covered)``.  Takes the best answer
        across the fan-out; stops early on full coverage."""
        tokens = [int(t) for t in np.asarray(tokens).reshape(-1)]
        start_blocks = max(int(start_blocks), 0)
        payload = {
            "op": "prefix_get",
            "tokens": tokens,
            "block_size": int(block_size),
            "max_blocks": int(max_blocks),
            "start": start_blocks,
        }
        best = None
        for _addr, reply in self._ask(payload):
            if not reply.get("hit"):
                continue
            covered = int(reply.get("covered") or 0)
            if best is None or covered > best[0]:
                try:
                    best = (
                        covered,
                        _decode_block(reply["k"]),
                        _decode_block(reply["v"]),
                        start_blocks,
                    )
                except (KeyError, ValueError):
                    continue  # malformed peer payload: ignore it
                if covered >= int(max_blocks):
                    break
        self._note_lookup(best is not None, "prefix")
        return best

    def gossip_now(self):
        """Push one gossip round to EVERY breaker-admitted peer: the
        local per-tenant admission deltas (fleet-wide quota accounting).
        Deltas a peer did not ACK — send failure, open breaker — are
        retained per-peer and retried next round, so a transient
        partition delays convergence instead of losing admissions.
        Returns the number of peers that acked."""
        engine = self._engine
        qos = getattr(engine, "qos", None) if engine else None
        fresh = qos.delta_counts() if qos is not None else {}
        peers = self.peers()
        with self._lock:
            for addr in peers:
                pending = self._pending_gossip.setdefault(addr, {})
                for tenant, n in fresh.items():
                    pending[tenant] = pending.get(tenant, 0) + n
            for addr in list(self._pending_gossip):
                if addr not in peers:  # departed peer: drop its backlog
                    del self._pending_gossip[addr]
        acked = 0
        for addr in peers:
            with self._lock:
                tenants = dict(self._pending_gossip.get(addr) or {})
            breaker = self._breakers.get(addr)
            try:
                breaker.before_attempt()
            except CircuitOpenError:
                continue
            try:
                self._peer_call(addr, {"op": "gossip", "tenants": tenants})
            except Exception:  # noqa: BLE001 - containment is the point
                breaker.record_failure()
                continue
            breaker.record_success()
            acked += 1
            with self._lock:
                pending = self._pending_gossip.get(addr)
                if pending is not None:
                    # subtract what was ACKED (concurrent rounds may have
                    # grown the backlog since the snapshot)
                    for tenant, n in tenants.items():
                        left = pending.get(tenant, 0) - n
                        if left > 0:
                            pending[tenant] = left
                        else:
                            pending.pop(tenant, None)
        with self._lock:
            self.gossip_rounds += 1
        self._count("ctpu_fleet_gossip_rounds_total")
        return acked

    def _gossip_loop(self, stop):
        while not stop.wait(self.gossip_interval_s):
            try:
                self.gossip_now()
            except Exception:  # pragma: no cover - defensive
                pass

    # -- replicated sequence state (the failure-domain lane) ---------------

    def _push(self, payload, nbytes=0, limit=None, stop=None, accept=None,
              candidates=None, until=None):
        """Push one payload to up to ``limit`` (default ``replicate_k``)
        breaker-admitted peers; returns the ack count.  ``nbytes`` > 0
        charges the anti-entropy byte budget FIRST (per peer) — the
        replication thread's rate bound.  ``accept(reply)``, when given,
        decides whether a peer's answer counts as an ack (a reachable
        peer that REJECTED the payload is not one; it is still breaker
        evidence of health).  ``candidates`` lets a caller that already
        admitted peers (consuming half-open probe slots) hand them in —
        an admitted candidate MUST have its outcome recorded, or the
        breaker's single-probe gate wedges.  ``until``, for calls that
        source their own candidates, keeps admitting ONE additional
        untried peer per widening wave until that many acks land (or no
        admissible peer remains): a quorum write must not refuse just
        because a first-wave candidate sits behind a partition while
        another peer is healthy.  Worst case stays bounded by
        ``len(peers) x timeout`` with per-peer breakers."""
        sourced = candidates is None
        if sourced:
            limit = self.replicate_k if limit is None else int(limit)
            candidates = self._candidates(limit=limit)
        tried = set()
        accepted = 0
        while True:
            for i, (addr, breaker) in enumerate(candidates):
                if nbytes and not self._budget_wait(nbytes, stop):
                    # shutting down mid-wait: release the remaining
                    # admitted half-open probe slots so no breaker stays
                    # wedged
                    for _addr, pending in candidates[i:]:
                        pending.record_failure()
                    return accepted
                tried.add(addr)
                try:
                    reply = self._traced_peer_call(addr, payload, breaker)
                except Exception:  # noqa: BLE001 - containment is the point
                    breaker.record_failure()
                    with self._lock:
                        self.peer_errors += 1
                    self._count("ctpu_fleet_peer_errors_total")
                    continue
                breaker.record_success()
                if accept is None or accept(reply):
                    accepted += 1
            if not sourced or until is None or accepted >= until:
                return accepted
            candidates = self._candidates(limit=1, exclude=tried)
            if not candidates:
                return accepted

    def publish_sequence(self, snapshot):
        """Replicate one durable sequence snapshot to ``replicate_k``
        peers SYNCHRONOUSLY — the engine calls this after applying a
        durable step and before the response reaches the wire, so an
        acked step survives this replica's unplanned death.  Bounded by
        k x lookup timeout with per-peer breakers: an unreachable fleet
        costs (almost) nothing and degrades to local-only durability.
        Returns the number of peers that STORED the snapshot — a peer
        that rejected it as stale is reachable but is no durability.
        Under ``quorum="majority"`` the push widens past the first-wave
        candidates until the quorum is met or every admissible peer was
        tried (see ``_push``'s ``until``)."""
        acked = self._push(
            {"op": "seq_put", "snapshot": snapshot},
            accept=lambda reply: bool(reply.get("stored")),
            until=self.seq_quorum_required() or None,
        )
        if acked:
            with self._lock:
                self.seq_pushes += 1
            self._count("ctpu_fleet_seq_snapshots_total")
        return acked

    def seq_quorum_required(self):
        """Peer-ack floor for a durable step under the configured quorum
        mode: 0 under ``"any"`` (best-effort: a partition degrades to
        local-only durability), ceil((K+1)/2) under ``"majority"`` — a
        majority of the K+1 copies (K peers + this replica) must hold
        the snapshot before the step may ack."""
        if self.quorum == "any":
            return 0
        return (self.replicate_k + 2) // 2

    def note_quorum(self, ok):
        """Record one quorum decision for a durable step (called by the
        engine at the ack/refuse site, NOT inside publish_sequence —
        drain-time exports also push snapshots but are not acks)."""
        with self._lock:
            if ok:
                self.seq_quorum_acks += 1
            else:
                self.seq_quorum_refusals += 1
        self._count(
            "ctpu_fleet_seq_quorum_acks_total" if ok
            else "ctpu_fleet_seq_quorum_refusals_total"
        )

    def quorum_evidence(self):
        """Breaker-state snapshot for the degraded-mode error message:
        which peers are open/half-open when a quorum write refuses."""
        states = self._breakers.states()
        return {
            addr: state for addr, state in states.items()
            if state != "closed"
        }

    def forget_sequence(self, seq_id):
        """A sequence ended cleanly: queue the drop so peers stop holding
        its snapshot (asynchronous — correctness never depends on it;
        stale entries also age out of the store)."""
        self.seq_store.pop(seq_id)
        if self.replicate_k > 0:
            # replicate_k=0 runs no replication thread: enqueueing onto
            # a never-drained queue would grow memory forever
            self._repl_queue.put(("seq_end", seq_id))

    def sequence_lookup(self, seq_id):
        """The freshest replicated snapshot for *seq_id*: the local
        store AND a bounded peer fan-out, newest version wins.  The
        local copy alone is never authoritative — with replicate_k
        below the fleet size each step's snapshot lands on a subset of
        peers, so a mid-sequence failover that trusted a local
        anti-entropy copy could resume steps behind the applied
        counter.  A peer hit is cached locally (stale-rejecting).
        None when nobody holds it."""
        best = local = self.seq_store.get(seq_id)
        for _addr, reply in self._ask(
            {"op": "seq_get", "sequence_id": seq_id}
        ):
            if not reply.get("hit"):
                continue
            snapshot = reply.get("snapshot") or {}
            if best is None or _seq_version(snapshot) > _seq_version(best):
                best = snapshot
        self._note_lookup(best is not None, "seq")
        if best is not None and best is not local:
            self.seq_store.put(best)
        return best

    # -- proactive replication / anti-entropy ------------------------------

    def note_cache_hit(self, key):
        """Host-side hot-entry signal from the front door's LOCAL cache
        hits (never a peer RPC): entries past ``hot_hits`` queue for the
        replication thread to push."""
        if self.replicate_k <= 0:
            return
        with self._lock:
            count = self._cache_hot.get(key, 0) + 1
            self._cache_hot[key] = count
            self._cache_hot.move_to_end(key)
            while len(self._cache_hot) > 4096:
                self._cache_hot.popitem(last=False)
            if count < self.hot_hits or key in self._cache_pushed:
                return
            self._cache_pushed.add(key)
            if len(self._cache_pushed) > 8192:
                self._cache_pushed.clear()  # bounded; worst case re-push
        self._repl_queue.put(("cache", key))

    def _budget_wait(self, nbytes, stop=None):
        """Charge *nbytes* against the byte/sec token bucket, sleeping
        (bounded, stop-aware) while the bucket is in debt.  Debt-based:
        one oversized item may overdraw, and the loop then waits the
        debt out — average push rate stays at the budget."""
        if self._repl_rate <= 0:
            return True  # unlimited
        while True:
            with self._lock:
                now = time.monotonic()
                self._repl_tokens = min(
                    self._repl_rate,
                    self._repl_tokens
                    + (now - self._repl_stamp) * self._repl_rate,
                )
                self._repl_stamp = now
                if self._repl_tokens > 0:
                    self._repl_tokens -= nbytes
                    return True
            if stop is None:
                return True  # synchronous replicate_now: no throttling
            if stop.wait(0.05):
                return False

    def _scan_hot(self):
        """Queue hot, not-yet-replicated prefix chains (store-lock only;
        the expensive encode is deferred to _replicate_one, which skips
        it while no peer is admissible)."""
        for row, n_blocks in self.store.take_hot(self.hot_hits):
            self._repl_queue.put(("prefix", row, n_blocks))

    def _replicate_one(self, item, stop=None):
        """Push one queued anti-entropy item to ``replicate_k`` peers.
        Returns the ack count (0 = nothing pushed; hot marks are cleared
        so later demand re-queues).  Peers are admitted BEFORE the
        expensive payload encode: with nobody reachable (no peers, every
        breaker open) the item is re-armed and dropped without paying
        the encode — an isolated or fully-degraded replica must not
        re-encode its hot set every scan interval forever."""
        kind = item[0]
        if kind == "seq_end":
            return self._push({"op": "seq_put", "ended": True,
                               "sequence_id": item[1]}, nbytes=256,
                              stop=stop)
        if kind == "cache":
            key = item[1]
            candidates = self._candidates(limit=self.replicate_k)
            if not candidates:
                with self._lock:
                    self._cache_pushed.discard(key)  # re-arm for later
                return 0
            engine = self._engine
            cache = (
                getattr(engine, "response_cache", None) if engine else None
            )
            value = cache.peek(key) if cache is not None else None
            if value is None:
                # evicted/expired since it ran hot: the admitted probe
                # slots must still resolve — ping keeps them honest
                self._push({"op": "ping"}, candidates=candidates)
                return 0
            response, blobs = value
            encoded = [
                base64.b64encode(bytes(b)).decode("ascii") for b in blobs
            ]
            nbytes = sum(len(b) for b in encoded) + len(
                json.dumps(response)
            ) + 256
            acked = self._push(
                {"op": "cache_put", "key": key, "response": response,
                 "blobs": encoded},
                nbytes=nbytes, stop=stop, candidates=candidates,
            )
            if not acked:
                with self._lock:
                    self._cache_pushed.discard(key)
            else:
                self._note_replicated("cache", nbytes, acked)
            return acked
        if kind == "prefix":
            row, n_blocks = item[1], item[2]
            candidates = self._candidates(limit=self.replicate_k)
            if not candidates:
                self.store.unmark_pushed(row)  # re-arm for later
                return 0
            block_size = self.store.block_size or 1
            got = self.store.lookup(row, block_size, n_blocks,
                                    count_hits=False)
            if got is None:
                self._push({"op": "ping"}, candidates=candidates)
                return 0  # evicted since the scan
            covered, k_layers, v_layers = got
            k_enc = _encode_block(k_layers)
            v_enc = _encode_block(v_layers)
            nbytes = sum(
                len(e["data"]) for e in k_enc + v_enc
            ) + 4 * len(row) + 256
            acked = self._push(
                {"op": "prefix_put", "tokens": list(row),
                 "n_blocks": covered, "block_size": block_size,
                 "k": k_enc, "v": v_enc},
                nbytes=nbytes, stop=stop, candidates=candidates,
            )
            if not acked:
                self.store.unmark_pushed(row)
            else:
                self._note_replicated("prefix", nbytes, acked)
            return acked
        return 0

    def _note_replicated(self, kind, nbytes, acked):
        with self._lock:
            self.replicated_items += 1
            self.replicated_bytes += nbytes * acked
        self._count("ctpu_fleet_replicated_items_total", {"kind": kind})
        self._count("ctpu_fleet_replicated_bytes_total",
                    value=nbytes * acked)

    def _replicate_loop(self, stop):
        """The anti-entropy thread: drains the push queue under the byte
        budget and, when idle, scans the prefix store for chains that ran
        hot.  Strictly OFF the request path — nothing here is ever
        awaited by a serving request."""
        while not stop.is_set():
            try:
                try:
                    item = self._repl_queue.get(
                        timeout=self.replicate_interval_s
                    )
                except queue.Empty:
                    self._scan_hot()
                    continue
                self._replicate_one(item, stop=stop)
            except Exception:  # a bad item must not kill anti-entropy
                pass

    def replicate_now(self):
        """Synchronously drain the anti-entropy queue (tests, benchmarks,
        pre-shutdown flushes).  Budget-exempt.  Returns items pushed."""
        self._scan_hot()
        pushed = 0
        while True:
            try:
                item = self._repl_queue.get_nowait()
            except queue.Empty:
                return pushed
            if self._replicate_one(item):
                pushed += 1

    # -- local store (host-side; no peer RPC, no device state) -------------

    def export_prefix(self, row, n_blocks, block_size, host_k, host_v):
        """Install *n_blocks* leading full blocks of the token row into
        this replica's host store (the LM engine calls this at prefill
        completion and at planned retire for parked streams — always
        OUTSIDE its condition lock; the arrays are already host-side)."""
        self.store.put(row, n_blocks, block_size, host_k, host_v)
        self._gauge()

    def local_summary(self):
        """The gossip/probe summary: most-recent chain digests, the
        response cache's digest keys (truncated to the summary limit),
        and the replica's autoscaling pressure signals."""
        engine = self._engine
        cache = getattr(engine, "response_cache", None) if engine else None
        cache_digests = (
            cache.keys()[-self.summary_limit:] if cache is not None else []
        )
        return {
            "prefix_digests": self.store.digests(self.summary_limit),
            "cache_digests": cache_digests,
            "pressure": self.pressure(),
        }

    def pressure(self):
        """Autoscaling signal bundle gossiped on probes: queued+inflight
        work on the attached engine, prefix-affinity pressure (hot
        chains held), and replicated sequences carried.  Host-side only
        — safe from the peer-server thread."""
        engine = self._engine
        queue_depth = 0
        if engine is not None:
            fn = getattr(engine, "pressure", None)
            if callable(fn):
                try:
                    queue_depth = int(fn().get("queue_depth", 0))
                except Exception:  # pragma: no cover - defensive
                    queue_depth = 0
        out = {
            "queue_depth": queue_depth,
            "prefix_hot": self.store.hot_count(self.hot_hits),
            "sequences": self.seq_store.count,
            "kv_used_fraction": self._kv_used_fraction(),
        }
        if self.registry is not None:
            self.registry.set(
                "ctpu_fleet_pressure_queue_depth", None, queue_depth,
                help_=FLEET_HELP["ctpu_fleet_pressure_queue_depth"],
            )
            self.registry.set(
                "ctpu_fleet_pressure_prefix", None, out["prefix_hot"],
                help_=FLEET_HELP["ctpu_fleet_pressure_prefix"],
            )
        return out

    def _kv_used_fraction(self):
        """Paged-KV occupancy (used / total blocks) from the registry
        gauges the KV pool publishes — block exhaustion is the earliest
        scale-up signal for LM workloads.  0.0 when no LM model is bound
        (no gauges) so the key is always present and comparable."""
        if self.registry is None:
            return 0.0
        used = self.registry.get("ctpu_lm_kv_blocks_used", None)
        free = self.registry.get("ctpu_lm_kv_blocks_free", None)
        if used is None or free is None:
            return 0.0
        total = float(used) + float(free)
        return round(float(used) / total, 4) if total > 0 else 0.0

    # -- metrics / introspection -------------------------------------------

    def _count(self, name, labels=None, value=1):
        if self.registry is not None:
            self.registry.inc(name, labels, value=value,
                              help_=FLEET_HELP[name])

    def _gauge(self):
        if self.registry is not None:
            self.registry.set(
                "ctpu_fleet_store_blocks", None, self.store.blocks,
                help_=FLEET_HELP["ctpu_fleet_store_blocks"],
            )

    def _note_lookup(self, hit, op):
        with self._lock:
            if hit:
                self.peer_hits += 1
            else:
                self.peer_misses += 1
        self._count(
            "ctpu_fleet_peer_hits_total" if hit
            else "ctpu_fleet_peer_misses_total",
            {"op": op},
        )

    def stats(self):
        store_blocks = self.store.blocks
        sequences = self.seq_store.count
        stale = self.seq_store.stale_rejected
        with self._lock:
            return {
                "peer_hits": self.peer_hits,
                "peer_misses": self.peer_misses,
                "peer_errors": self.peer_errors,
                "peer_skips": self.peer_skips,
                "gossip_rounds": self.gossip_rounds,
                "served": self.served,
                "store_blocks": store_blocks,
                "sequences": sequences,
                "seq_pushes": self.seq_pushes,
                "seq_stale_rejected": stale,
                "seq_quorum_acks": self.seq_quorum_acks,
                "seq_quorum_refusals": self.seq_quorum_refusals,
                "replicated_items": self.replicated_items,
                "replicated_bytes": self.replicated_bytes,
                "peers": list(self._peers),
            }
