"""Prometheus-style metrics for the in-process server.

The TPU-native analog of Triton's GPU metrics endpoint (the reference's
MetricsManager scrapes ``nv_gpu_utilization`` / ``nv_gpu_memory_*`` from the
server's /metrics — reference metrics_manager.h:44-91): per-model inference
counters and durations from the engine's statistics, plus per-TPU-device HBM
usage via ``device.memory_stats()`` where the PJRT runtime exposes it (the
tunneled axon platform reports none; real TPU VMs report bytes_in_use /
bytes_limit).
"""

import time


def _device_lines(lines):
    try:
        import jax

        devices = jax.devices()
    except Exception:
        return
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        labels = f'{{device="{d.id}",kind="{d.device_kind}"}}'
        used = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        peak = stats.get("peak_bytes_in_use")
        if used is not None:
            lines.append(
                f"ctpu_tpu_memory_used_bytes{labels} {used}"
            )
        if limit is not None:
            lines.append(
                f"ctpu_tpu_memory_total_bytes{labels} {limit}"
            )
        if peak is not None:
            lines.append(
                f"ctpu_tpu_memory_peak_bytes{labels} {peak}"
            )


def render_metrics(engine):
    """The /metrics payload (Prometheus text exposition format)."""
    lines = [
        "# HELP ctpu_inference_request_success Successful inference requests",
        "# TYPE ctpu_inference_request_success counter",
        "# HELP ctpu_inference_request_failure Failed inference requests",
        "# TYPE ctpu_inference_request_failure counter",
        "# HELP ctpu_inference_count Inferences performed (batch aware)",
        "# TYPE ctpu_inference_count counter",
        "# HELP ctpu_inference_duration_us Cumulative request duration",
        "# TYPE ctpu_inference_duration_us counter",
        "# HELP ctpu_tpu_memory_used_bytes Device HBM bytes in use",
        "# TYPE ctpu_tpu_memory_used_bytes gauge",
        "# HELP ctpu_server_busy_ns Wall-clock ns with >=1 model execution in"
        " flight (duty cycle: rate(ctpu_server_busy_ns)/1e9 = utilization)",
        "# TYPE ctpu_server_busy_ns counter",
    ]
    stats = engine.statistics()
    # engine.statistics() returns the HTTP-format bare list of model entries
    model_stats = stats if isinstance(stats, list) else stats.get(
        "model_stats", []
    )
    for ms in model_stats:
        model = ms.get("name", "")
        version = ms.get("version", "")
        labels = f'{{model="{model}",version="{version}"}}'
        agg = ms.get("inference_stats", {})
        success = agg.get("success", {})
        fail = agg.get("fail", {})
        lines.append(
            f"ctpu_inference_request_success{labels} "
            f"{int(success.get('count', 0))}"
        )
        lines.append(
            f"ctpu_inference_request_failure{labels} "
            f"{int(fail.get('count', 0))}"
        )
        lines.append(
            f"ctpu_inference_count{labels} "
            f"{int(ms.get('inference_count', 0))}"
        )
        lines.append(
            f"ctpu_inference_duration_us{labels} "
            f"{int(success.get('ns', 0)) // 1000}"
        )
    _device_lines(lines)
    busy = getattr(engine, "busy", None)
    if busy is not None:
        lines.append(f"ctpu_server_busy_ns {busy.busy_ns()}")
    lines.append(f"ctpu_scrape_timestamp_seconds {time.time():.3f}")
    return "\n".join(lines) + "\n"
