"""Prometheus-style metrics for the in-process server.

The TPU-native analog of Triton's GPU metrics endpoint (the reference's
MetricsManager scrapes ``nv_gpu_utilization`` / ``nv_gpu_memory_*`` from the
server's /metrics — reference metrics_manager.h:44-91), grown into the full
observability surface:

- per-model counters (success/failure/inference counts, success AND failure
  cumulative durations, the per-phase queue/compute_input/compute_infer/
  compute_output breakdown the statistics extension measures),
- per-model latency **histograms** (request duration, queue time) and the
  batch-size distribution,
- live gauges (batcher queue depth per model, in-flight requests, draining),
- resilience counters (requests shed with retryable 503s, drain events) and
  — when clients in this process attach a :class:`ResilienceMetricsObserver`
  to their retry policy / circuit breaker — client-side retry counters and
  per-endpoint circuit state,
- per-TPU-device HBM usage via ``device.memory_stats()`` where the PJRT
  runtime exposes it,
- the continuous-batching LM engine's series (serve/lm, bound into this
  registry at add_model time): ``ctpu_lm_kv_blocks_{used,free}`` (paged
  KV pool occupancy), ``ctpu_lm_lanes`` / ``ctpu_lm_active_lanes``
  (autoscaled decode lane count vs lanes streaming),
  ``ctpu_lm_tokens_total`` and ``ctpu_lm_prefill_chunks_total``, plus
  the KV **prefix cache** and **preemption** series (:data:`LM_PREFIX_HELP`
  below): ``ctpu_lm_prefix_{hits,misses,evictions}_total`` (blocks
  adopted / shareable-but-cold / evicted under pool pressure),
  ``ctpu_lm_prefix_cached_blocks``, the prefill-compute accounting pair
  ``ctpu_lm_prefill_tokens_total`` / ``ctpu_lm_prefill_tokens_saved_total``
  (the perf/bench ``prefix_hit_pct`` numerators), and
  ``ctpu_lm_preemptions_total`` / ``ctpu_lm_swapped_blocks`` (lanes
  swapped to the host store under priority pressure), and the
  **speculative decoding** series (:data:`LM_SPEC_HELP`):
  ``ctpu_lm_spec_{proposed,accepted,rejected}_tokens_total`` +
  ``ctpu_lm_spec_acceptance_rate`` — draft/verify outcomes when a model
  enables ``speculative={...}``.

Every label value passes through :func:`escape_label`: the exposition format
reserves ``\\``, ``"`` and newline inside quoted label values, and a model
name containing any of them must not corrupt the whole scrape.
"""

import bisect
import threading
import time

from client_tpu.utils import escape_label  # noqa: F401  (canonical re-export)

# Request/queue duration buckets (microseconds) and batch-size buckets.
DURATION_BUCKETS_US = (
    50, 100, 250, 500, 1000, 2500, 5000, 10000, 25000, 50000,
    100000, 250000, 500000, 1000000, 2500000, 10000000,
)
BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

# CircuitBreaker state -> gauge value (closed/half-open/open).
CIRCUIT_STATE_VALUES = {"closed": 0, "half-open": 1, "open": 2}

# Endpoint health state -> gauge value (client_tpu.utils server states).
ENDPOINT_STATE_VALUES = {"READY": 0, "NOT_READY": 1, "UNREACHABLE": 2}

# Endpoint membership phase -> gauge value (client_tpu.balance.pool).
ENDPOINT_PHASE_VALUES = {"active": 0, "probation": 1, "retiring": 2}

# LM prefix-cache + preemption series (written by serve/lm/prefix.py and
# serve/lm/engine.py into whichever registry the engine is bound to; the
# help text lives here so the catalog has one source of truth).
LM_PREFIX_HELP = {
    "ctpu_lm_prefix_hits_total":
        "Prompt-prefix KV blocks adopted by reference from the cache",
    "ctpu_lm_prefix_misses_total":
        "Shareable full prompt blocks that had no cached match",
    "ctpu_lm_prefix_evictions_total":
        "Cached prefix blocks evicted under pool pressure",
    "ctpu_lm_prefix_cached_blocks":
        "KV blocks currently held warm by the prefix cache",
    "ctpu_lm_prefill_tokens_total":
        "Prompt tokens actually computed by prefill chunks",
    "ctpu_lm_prefill_tokens_saved_total":
        "Prompt tokens skipped via prefix-cache adoption",
    "ctpu_lm_preemptions_total":
        "Decode lanes preempted (KV swapped out) under priority pressure",
    "ctpu_lm_swapped_blocks":
        "KV blocks currently parked in the host-side swap store",
}

# Speculative-decoding series (written by serve/lm/engine.py's verify
# pass when a model enables ``speculative={...}``; serve/lm/spec.py owns
# the drafter/adaptive-k policy).  Acceptance rate is the cumulative
# accepted/proposed ratio — the per-lane adaptive controller uses its
# own rolling window.
LM_SPEC_HELP = {
    "ctpu_lm_spec_proposed_tokens_total":
        "Draft tokens proposed to the speculative verify tick",
    "ctpu_lm_spec_accepted_tokens_total":
        "Draft tokens the verify tick accepted (target-model-exact)",
    "ctpu_lm_spec_rejected_tokens_total":
        "Draft tokens the verify tick rejected (KV rewound, not leaked)",
    "ctpu_lm_spec_acceptance_rate":
        "Cumulative speculative acceptance rate (accepted / proposed)",
}

# SLO watchdog + flight recorder series (written by serve/slo.py and
# serve/flight.py into the engine registry; one help catalog so
# /metrics, README, bench and tests agree).
SLO_HELP = {
    "ctpu_slo_p50_ms":
        "Windowed p50 request latency per model/tenant (sketch quantile)",
    "ctpu_slo_p95_ms":
        "Windowed p95 request latency per model/tenant (sketch quantile)",
    "ctpu_slo_p99_ms":
        "Windowed p99 request latency per model/tenant (sketch quantile)",
    "ctpu_slo_error_rate":
        "Windowed server-fault rate per model/tenant (5xx/transport only)",
    "ctpu_slo_breaches_total":
        "SLO objective breaches (by model/tenant and objective kind)",
    "ctpu_flight_dumps_total":
        "Flight-recorder dumps written (by trigger reason)",
}

# Fleet cache-tier series (written by serve/fleet.py and the fleet hooks
# in serve/lm/engine.py + model_runtime into whichever registry the tier
# is bound to; one help catalog so /metrics, README and tests agree).
FLEET_HELP = {
    "ctpu_fleet_peer_hits_total":
        "Peer lookups answered with content (by op: cache/prefix)",
    "ctpu_fleet_peer_misses_total":
        "Peer lookups every reachable peer missed (by op)",
    "ctpu_fleet_peer_errors_total":
        "Peer RPCs that failed or timed out (circuit strikes)",
    "ctpu_fleet_peer_skips_total":
        "Peer lookups skipped behind an open per-peer circuit",
    "ctpu_fleet_prefix_blocks_total":
        "KV prefix blocks installed from a peer replica's cache tier",
    "ctpu_fleet_prefix_tokens_saved_total":
        "Prefill tokens skipped via peer-fetched KV prefix blocks",
    "ctpu_fleet_cache_hits_total":
        "Unary responses served from a peer replica's response cache",
    "ctpu_fleet_store_blocks":
        "KV blocks exported into this replica's host-side fleet store",
    "ctpu_fleet_gossip_rounds_total":
        "Fleet gossip rounds pushed (tenant counters + digest summaries)",
    "ctpu_fleet_sessions_migrated_total":
        "Parked LM streams exported to the fleet tier at planned retire",
    "ctpu_fleet_seq_snapshots_total":
        "Durable sequence snapshots pushed to peer replicas",
    "ctpu_fleet_seq_resumes_total":
        "Sequences resumed from a fleet-replicated snapshot",
    "ctpu_fleet_seq_stale_total":
        "Stale sequence snapshots rejected by the replicated store",
    "ctpu_fleet_seq_heals_total":
        "Skips-ahead gaps healed by re-looking up a fresher snapshot",
    "ctpu_fleet_replicated_items_total":
        "Anti-entropy items proactively pushed to peers (by kind)",
    "ctpu_fleet_replicated_bytes_total":
        "Anti-entropy payload bytes proactively pushed to peers",
    "ctpu_fleet_pressure_queue_depth":
        "Gossiped per-replica queued+inflight work (autoscaling signal)",
    "ctpu_fleet_pressure_prefix":
        "Gossiped per-replica prefix-affinity pressure (hot chains held)",
    "ctpu_fleet_seq_quorum_acks_total":
        "Durable sequence steps acked with write quorum satisfied",
    "ctpu_fleet_seq_quorum_refusals_total":
        "Durable sequence steps refused (503) for unreachable quorum",
}

# Continuous-profiler series (written by serve/prof.py's PhaseProfiler
# into whichever registry the profiler is bound to; engine label is the
# profiler name — "serve" for the unary engine, "lm" for an LM
# scheduler, "perf_client" for the perf harness's client-side splits).
PROF_HELP = {
    "ctpu_prof_ticks_total":
        "Profiler ticks committed (by engine and tick kind)",
    "ctpu_prof_phase_seconds_total":
        "Cumulative seconds attributed to each profiled phase",
    "ctpu_prof_mfu_pct":
        "Model FLOP utilization over measured device time (vs "
        "device_peak_tflops; cpu_fallback peak off-TPU)",
    "ctpu_prof_compute_share_pct":
        "Share of measured device time attributed to each model",
}

# Autoscaler control-loop series (written by serve/autoscale.py into the
# registry it is constructed with).
AUTOSCALE_HELP = {
    "ctpu_autoscale_scale_ups_total":
        "Autoscaler scale-up actions taken (replicas spawned)",
    "ctpu_autoscale_scale_downs_total":
        "Autoscaler scale-down actions taken (replicas drained+retired)",
    "ctpu_autoscale_flap_suppressed_total":
        "Autoscaler decisions suppressed by cooldown/hysteresis",
    "ctpu_autoscale_replicas":
        "Current replica count the autoscaler is steering",
}


def format_labels(labels):
    """{'model': 'm'} -> '{model="m"}' with every value escaped."""
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Histogram:
    """Fixed-bucket histogram (Prometheus semantics: cumulative buckets at
    render time, plus sum and count).  Not internally locked — callers
    (ModelStats) guard observations with their own lock."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets=DURATION_BUCKETS_US):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        self.counts[bisect.bisect_left(self.buckets, value)] += 1
        self.sum += value
        self.count += 1

    def snapshot(self):
        """(bucket_bounds, cumulative_counts, sum, count)."""
        cumulative = []
        total = 0
        for c in self.counts:
            total += c
            cumulative.append(total)
        return self.buckets, cumulative, self.sum, self.count


class Registry:
    """Thread-safe counter/gauge registry rendering to exposition format.

    One instance per engine holds server-side series (sheds, drain); the
    module-level :data:`RESILIENCE` registry holds client-side series fed
    by :class:`ResilienceMetricsObserver` so in-process clients' retry and
    circuit activity is scrapeable from the same /metrics payload.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._families = {}  # name -> {"type","help","samples":{labels:v}}

    def _family(self, name, type_, help_):
        fam = self._families.get(name)
        if fam is None:
            fam = {"type": type_, "help": help_, "samples": {}}
            self._families[name] = fam
        return fam

    def inc(self, name, labels=None, value=1, help_=""):
        key = format_labels(labels)
        with self._lock:
            samples = self._family(name, "counter", help_)["samples"]
            samples[key] = samples.get(key, 0) + value

    def set(self, name, labels=None, value=0.0, help_=""):
        key = format_labels(labels)
        with self._lock:
            self._family(name, "gauge", help_)["samples"][key] = value

    def get(self, name, labels=None):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return None
            return fam["samples"].get(format_labels(labels))

    def remove(self, name, labels=None):
        """Drop one labeled sample (gauges for departed label values —
        e.g. an evicted endpoint's phase/state — must not sit on /metrics
        at their last value forever, nor accumulate without bound under
        membership churn)."""
        key = format_labels(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                fam["samples"].pop(key, None)

    def render_into(self, lines):
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                lines.append(f"# HELP {name} {fam['help'] or name}")
                lines.append(f"# TYPE {name} {fam['type']}")
                for labels, value in sorted(fam["samples"].items()):
                    lines.append(f"{name}{labels} {_fmt(value)}")


# Client-side resilience series (retries, circuit state) for clients
# living in the same process as the server — the hermetic/in-process
# deployment this framework's fake-server role serves.
RESILIENCE = Registry()


class ResilienceMetricsObserver:
    """Adapter feeding resilience events into a metrics registry.

    Attach one instance per endpoint as BOTH the retry-policy observer and
    the circuit-breaker observer::

        obs = ResilienceMetricsObserver("127.0.0.1:8000")
        breaker = CircuitBreaker(observer=obs)
        policy = RetryPolicy(circuit_breaker=breaker, observer=obs)
    """

    def __init__(self, endpoint, registry=None):
        self.endpoint = endpoint
        self.registry = registry if registry is not None else RESILIENCE
        self.registry.set(
            "ctpu_client_circuit_state", {"endpoint": endpoint}, 0,
            help_="Circuit breaker state per endpoint "
                  "(0=closed, 1=half-open, 2=open)",
        )

    # retry-policy hooks -----------------------------------------------------

    def on_backoff(self, attempt, delay_s, exc):
        self.registry.inc(
            "ctpu_client_retries_total", {"endpoint": self.endpoint},
            help_="Client retry attempts (one per backoff sleep)",
        )

    def on_giveup(self, attempt, exc):
        self.registry.inc(
            "ctpu_client_request_failures_total",
            {"endpoint": self.endpoint},
            help_="Client calls that exhausted their retry policy",
        )

    def on_success(self, attempt):
        pass

    # circuit-breaker hook ---------------------------------------------------

    def on_state_change(self, old, new):
        self.registry.set(
            "ctpu_client_circuit_state", {"endpoint": self.endpoint},
            CIRCUIT_STATE_VALUES.get(new, -1),
            help_="Circuit breaker state per endpoint "
                  "(0=closed, 1=half-open, 2=open)",
        )
        self.registry.inc(
            "ctpu_client_circuit_transitions_total",
            {"endpoint": self.endpoint, "to": new},
            help_="Circuit breaker state transitions",
        )


class BalancerMetricsObserver:
    """Adapter feeding replica-set routing events into a metrics registry.

    Attach one instance as the ``observer`` of a
    ``client_tpu.balance.EndpointPool``::

        obs = BalancerMetricsObserver()
        pool = EndpointPool(urls, observer=obs)

    Series (all per-endpoint): ``ctpu_client_routed_total`` (requests the
    balancer sent to each replica — the convergence proof when replicas
    die), ``ctpu_client_failovers_total`` (attempts that failed retryably
    on a replica and rotated off it), ``ctpu_client_endpoint_state``
    (the pool's READY/NOT_READY/UNREACHABLE health view),
    ``ctpu_client_endpoint_phase`` (membership lifecycle:
    active/probation/retiring), ``ctpu_client_membership_changes_total``
    (discovery add/retire/unretire/promote/retain/evict events),
    ``ctpu_client_pool_endpoints`` (pool size per phase), and the
    streaming-reconnect pair ``ctpu_client_stream_reconnects_total`` /
    ``ctpu_client_stream_replayed_requests_total``.
    """

    def __init__(self, registry=None):
        self.registry = registry if registry is not None else RESILIENCE

    def on_route(self, endpoint):
        self.registry.inc(
            "ctpu_client_routed_total", {"endpoint": endpoint},
            help_="Requests routed to each replica by the client balancer",
        )

    def on_failover(self, endpoint):
        self.registry.inc(
            "ctpu_client_failovers_total", {"endpoint": endpoint},
            help_="Attempts that failed retryably on a replica and were "
                  "failed over",
        )

    def on_endpoint_state(self, endpoint, state):
        self.registry.set(
            "ctpu_client_endpoint_state", {"endpoint": endpoint},
            ENDPOINT_STATE_VALUES.get(state, -1),
            help_="Pool health view per endpoint "
                  "(0=ready, 1=not-ready/draining, 2=unreachable)",
        )

    # membership / discovery hooks -------------------------------------------

    def on_endpoint_phase(self, endpoint, phase):
        self.registry.set(
            "ctpu_client_endpoint_phase", {"endpoint": endpoint},
            ENDPOINT_PHASE_VALUES.get(phase, -1),
            help_="Pool membership phase per endpoint "
                  "(0=active, 1=probation, 2=retiring)",
        )

    def on_membership(self, op, endpoint):
        self.registry.inc(
            "ctpu_client_membership_changes_total",
            {"op": op, "endpoint": endpoint},
            help_="Discovery-driven membership events "
                  "(add/retire/unretire/promote/retain/evict)",
        )
        if op == "evict":
            # the endpoint is gone: its per-endpoint gauges must not park
            # at their last value (counters stay — they are history)
            labels = {"endpoint": endpoint}
            self.registry.remove("ctpu_client_endpoint_phase", labels)
            self.registry.remove("ctpu_client_endpoint_state", labels)
            self.registry.remove("ctpu_fleet_pressure_queue_depth", labels)
            self.registry.remove("ctpu_fleet_pressure_prefix", labels)

    def on_endpoint_pressure(self, endpoint, pressure):
        """Gossiped autoscaling signals (probe-piggybacked; see
        ``FleetTier.local_summary`` / ``EndpointPool.set_pressure``)."""
        labels = {"endpoint": endpoint}
        self.registry.set(
            "ctpu_fleet_pressure_queue_depth", labels,
            float(pressure.get("queue_depth", 0) or 0),
            help_=FLEET_HELP["ctpu_fleet_pressure_queue_depth"],
        )
        self.registry.set(
            "ctpu_fleet_pressure_prefix", labels,
            float(pressure.get("prefix_hot", 0) or 0),
            help_=FLEET_HELP["ctpu_fleet_pressure_prefix"],
        )

    def on_pool_size(self, active, probation, retiring):
        for phase, count in (
            ("active", active), ("probation", probation),
            ("retiring", retiring),
        ):
            self.registry.set(
                "ctpu_client_pool_endpoints", {"phase": phase}, count,
                help_="Replica-set pool size per membership phase",
            )

    # streaming-reconnect hooks ----------------------------------------------

    def on_stream_reconnect(self, endpoint):
        self.registry.inc(
            "ctpu_client_stream_reconnects_total", {"endpoint": endpoint},
            help_="Streams that died connection-level on this replica and "
                  "reconnected to a fresh one",
        )

    def on_stream_replayed(self, endpoint, count):
        self.registry.inc(
            "ctpu_client_stream_replayed_requests_total",
            {"endpoint": endpoint}, value=count,
            help_="Unacknowledged stream requests replayed onto this "
                  "replica after a reconnect",
        )


def _fmt(value):
    if isinstance(value, float) and not value.is_integer():
        return f"{value:.6f}"
    return str(int(value))


class _FamilyBuffer:
    """Groups samples per metric family so the exposition output keeps all
    lines of one family contiguous (required by the text format — parsers
    keying families by name reject or drop interleaved groups)."""

    def __init__(self):
        self._families = {}  # name -> [type, help, [sample lines]]

    def declare(self, name, type_, help_):
        self._families.setdefault(name, [type_, help_, []])

    def add(self, name, labels, value):
        self._families[name][2].append(
            f"{name}{format_labels(labels)} {_fmt(value)}"
        )

    def add_raw(self, name, line):
        self._families[name][2].append(line)

    def emit(self, lines):
        for name, (type_, help_, samples) in self._families.items():
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {type_}")
            lines.extend(samples)


def _device_lines(buf):
    # Only report devices when jax is already loaded: a server actually
    # serving jax models has it imported; forcing the import (and backend
    # init — seconds) inside the /metrics handler would stall the first
    # scrape of every numpy-only server past typical scraper timeouts.
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return
    try:
        devices = jax.devices()
    except Exception:
        return
    declared = False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        labels = {"device": d.id, "kind": d.device_kind}
        used = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        peak = stats.get("peak_bytes_in_use")
        if not declared and (
            used is not None or limit is not None or peak is not None
        ):
            declared = True
            buf.declare(
                "ctpu_tpu_memory_used_bytes", "gauge",
                "Device HBM bytes in use",
            )
            buf.declare(
                "ctpu_tpu_memory_total_bytes", "gauge",
                "Device HBM byte capacity",
            )
            buf.declare(
                "ctpu_tpu_memory_peak_bytes", "gauge",
                "Peak device HBM bytes",
            )
        if used is not None:
            buf.add("ctpu_tpu_memory_used_bytes", labels, used)
        if limit is not None:
            buf.add("ctpu_tpu_memory_total_bytes", labels, limit)
        if peak is not None:
            buf.add("ctpu_tpu_memory_peak_bytes", labels, peak)


def _histogram_lines(buf, name, labels, snapshot):
    buckets, cumulative, total, count = snapshot
    for bound, c in zip(buckets, cumulative[:-1]):
        le = format_labels(dict(labels, le=bound))
        buf.add_raw(name, f"{name}_bucket{le} {c}")
    inf = format_labels(dict(labels, le="+Inf"))
    buf.add_raw(name, f"{name}_bucket{inf} {cumulative[-1]}")
    lbl = format_labels(labels)
    buf.add_raw(name, f"{name}_sum{lbl} {_fmt(total)}")
    buf.add_raw(name, f"{name}_count{lbl} {count}")


_COUNTER_HELP = [
    ("ctpu_inference_request_success", "Successful inference requests"),
    ("ctpu_inference_request_failure", "Failed inference requests"),
    ("ctpu_inference_count", "Inferences performed (batch aware)"),
    ("ctpu_inference_exec_count", "Model executions (batches count once)"),
    ("ctpu_inference_duration_us",
     "Cumulative successful request duration"),
    ("ctpu_inference_fail_duration_us",
     "Cumulative failed request duration"),
    ("ctpu_inference_queue_duration_us",
     "Cumulative scheduling-queue wait"),
    ("ctpu_inference_compute_input_duration_us",
     "Cumulative input-preparation time"),
    ("ctpu_inference_compute_infer_duration_us",
     "Cumulative model-execution time"),
    ("ctpu_inference_compute_output_duration_us",
     "Cumulative output-rendering time"),
]

_HISTOGRAM_HELP = [
    ("ctpu_request_duration_us",
     "Per-request end-to-end duration distribution"),
    ("ctpu_queue_duration_us",
     "Per-request dynamic-batcher queue-time distribution"),
    ("ctpu_batch_size", "Execution batch-size (rows) distribution"),
]


def render_metrics(engine):
    """The /metrics payload (Prometheus text exposition format).

    All samples of one metric family are emitted as a single contiguous
    block (HELP/TYPE then every sample) — the text format requires it, and
    family-keyed parsers drop or reject interleaved groups."""
    buf = _FamilyBuffer()
    for name, help_ in _COUNTER_HELP:
        buf.declare(name, "counter", help_)
    stats = engine.statistics()
    # engine.statistics() returns the HTTP-format bare list of model entries
    model_stats = stats if isinstance(stats, list) else stats.get(
        "model_stats", []
    )
    for ms in model_stats:
        labels = {"model": ms.get("name", ""), "version": ms.get("version", "")}
        agg = ms.get("inference_stats", {})
        success = agg.get("success", {})
        fail = agg.get("fail", {})
        buf.add(
            "ctpu_inference_request_success", labels,
            int(success.get("count", 0)),
        )
        buf.add(
            "ctpu_inference_request_failure", labels,
            int(fail.get("count", 0)),
        )
        buf.add("ctpu_inference_count", labels, int(ms.get("inference_count", 0)))
        buf.add(
            "ctpu_inference_exec_count", labels,
            int(ms.get("execution_count", 0)),
        )
        buf.add(
            "ctpu_inference_duration_us", labels,
            int(success.get("ns", 0)) // 1000,
        )
        buf.add(
            "ctpu_inference_fail_duration_us", labels,
            int(fail.get("ns", 0)) // 1000,
        )
        for phase in ("queue", "compute_input", "compute_infer",
                      "compute_output"):
            buf.add(
                f"ctpu_inference_{phase}_duration_us", labels,
                int(agg.get(phase, {}).get("ns", 0)) // 1000,
            )
    # per-model histograms (request/queue durations, batch sizes)
    for name, help_ in _HISTOGRAM_HELP:
        buf.declare(name, "histogram", help_)
    for name, version, model_stats_obj in engine.stats_objects():
        labels = {"model": name, "version": version}
        request_us, queue_us, batch_rows = model_stats_obj.histograms()
        _histogram_lines(buf, "ctpu_request_duration_us", labels, request_us)
        _histogram_lines(buf, "ctpu_queue_duration_us", labels, queue_us)
        _histogram_lines(buf, "ctpu_batch_size", labels, batch_rows)
    # live gauges: scheduler queue depth, in-flight work, drain state
    buf.declare(
        "ctpu_queue_depth", "gauge",
        "Requests waiting in the dynamic batcher",
    )
    for name, depth in sorted(engine.queue_depths().items()):
        buf.add("ctpu_queue_depth", {"model": name}, depth)
    tenant_depths = getattr(engine, "tenant_queue_depths", None)
    if tenant_depths is not None:
        buf.declare(
            "ctpu_tenant_queue_depth", "gauge",
            "Requests waiting per tenant fair-queue lane",
        )
        for (model, tenant), depth in sorted(tenant_depths().items()):
            buf.add(
                "ctpu_tenant_queue_depth",
                {"model": model, "tenant": tenant}, depth,
            )
    buf.declare(
        "ctpu_inflight_requests", "gauge", "Requests currently executing"
    )
    buf.add("ctpu_inflight_requests", None, engine.inflight_count())
    buf.declare("ctpu_draining", "gauge", "1 once graceful drain has begun")
    buf.add("ctpu_draining", None, 0 if engine.ready() else 1)
    _device_lines(buf)
    busy = getattr(engine, "busy", None)
    if busy is not None:
        buf.declare(
            "ctpu_server_busy_ns", "counter",
            "Wall-clock ns with >=1 model execution in flight (duty cycle: "
            "rate(ctpu_server_busy_ns)/1e9 = utilization)",
        )
        buf.add("ctpu_server_busy_ns", None, busy.busy_ns())
    buf.declare(
        "ctpu_scrape_timestamp_seconds", "gauge",
        "Wall time of this scrape",
    )
    buf.add_raw(
        "ctpu_scrape_timestamp_seconds",
        f"ctpu_scrape_timestamp_seconds {time.time():.3f}",
    )
    lines = []
    buf.emit(lines)
    # engine-side resilience counters (sheds, drain events) + any client
    # resilience series registered in this process — each registry renders
    # its families as contiguous blocks of its own
    engine.metrics.render_into(lines)
    RESILIENCE.render_into(lines)
    return "\n".join(lines) + "\n"
