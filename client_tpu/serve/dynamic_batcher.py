"""Cross-request dynamic micro-batching for stateless batchable models.

TPU-first rationale: the MXU wants large batched matmuls/convs, and every
device round trip (H2D, dispatch, D2H) carries fixed latency — per-request
execution pays that latency per request, a batcher pays it per *batch*.  This
is the server-side analog of the dynamic batcher in the reference's server
ecosystem (the client-side reference exposes it via model config
``dynamic_batching``; model_parser.h:59-193 normalizes scheduler kinds), built
the XLA way: batches are padded to power-of-two buckets so every batch size
hits an already-compiled executable instead of triggering a retrace.

Eligibility: stateless, non-decoupled models with ``max_batch_size > 1`` and
host-resident (wire) inputs.  Shared-memory requests keep the direct
zero-copy path — batching them would force device→host materialization.
"""

import sys
import threading
import time
from collections import deque

import numpy as np

from client_tpu.utils import InferenceServerException


def _bucket(n, cap):
    """Smallest bucket >= n from {2^k, 3*2^k}, capped at cap.

    The 1.5x intermediate sizes keep worst-case padding waste to 33% instead
    of 100% while the bucket count (and so the compile count) stays O(log n).
    """
    b = 1
    while b < n:
        if b * 3 // 2 >= n and b >= 2:
            b = b * 3 // 2
            break
        b *= 2
    return min(b, cap)


def _buckets_up_to(cap):
    """All bucket sizes warmup must cover, ending exactly at cap."""
    out = []
    b = 1
    while b < cap:
        out.append(b)
        if b >= 2 and b * 3 // 2 < cap:
            out.append(b * 3 // 2)
        b *= 2
    out.append(cap)
    return sorted(set(out))


def _is_device_array(arr):
    """jax.Array check without importing jax on the host-only path."""
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(arr, jax.Array)


class _Pending:
    __slots__ = ("inputs", "rows", "signature", "event", "result", "error", "t_enq")

    def __init__(self, inputs, rows, signature):
        self.inputs = inputs
        self.rows = rows
        self.signature = signature
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.t_enq = time.monotonic_ns()


class ModelBatcher:
    """One background batcher per model: gathers concurrent requests into a
    single padded forward pass and splits the host-materialized outputs."""

    def __init__(self, model, stats, max_queue_delay_s=0.003, busy=None):
        self.model = model
        self.stats = stats
        self._busy = busy  # engine BusyTracker (duty-cycle metric), optional
        self.max_batch = max(int(model.max_batch_size), 1)
        self.max_queue_delay_s = max_queue_delay_s
        self._cond = threading.Condition()
        self._queue = deque()
        # Requests popped off the queue but not yet completed/failed (gathered
        # group + the in-flight pipelined batch).  Tracked so the _loop
        # BaseException handler can fail them too — otherwise a KeyboardInterrupt
        # /MemoryError between _gather and _fail strands those waiters forever.
        self._active = set()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name=f"batcher-{model.name}", daemon=True
        )
        self._thread.start()

    def warmup(self, input_specs):
        """Pre-compile every padded bucket (the reference's ``model_warmup``
        analog): run the model on zeros for each power-of-two batch size so no
        client request ever pays a compile.  Skipped for models with dynamic
        non-batch dims."""
        from client_tpu.utils import triton_to_np_dtype

        shapes = {}
        for spec in input_specs:
            dims = list(spec.dims)
            if any(d < 0 for d in dims[1:]):
                return
            np_dtype = triton_to_np_dtype(spec.datatype)
            if np_dtype is None or np_dtype == np.object_:
                return
            shapes[spec.name] = (dims[1:], np_dtype)
        buckets = _buckets_up_to(self.max_batch)
        import jax

        for b in buckets:
            zeros = {
                name: np.zeros([b] + dims, dtype=np_dtype)
                for name, (dims, np_dtype) in shapes.items()
            }
            jax.device_get(self.model.fn(zeros, {}, None))

    # -- request side -----------------------------------------------------

    def submit(self, inputs):
        """Block until the batched execution finishes; return this request's
        slice of the outputs — host numpy arrays for wire groups, live device
        slices for device (TPU-shm) groups."""
        rows = _leading_rows(inputs)
        # Device-resident requests batch with the jnp path (concat + split on
        # device, no transfers) and must never mix with host groups — the
        # signature's device flag keeps the populations apart.
        device = all(_is_device_array(a) for a in inputs.values())
        signature = (device,) + tuple(
            (name, arr.dtype.str, tuple(arr.shape[1:]))
            for name, arr in sorted(inputs.items())
        )
        pending = _Pending(inputs, rows, signature)
        with self._cond:
            if self._closed:
                raise InferenceServerException(
                    f"model '{self.model.name}' is shutting down", status="500"
                )
            self._queue.append(pending)
            self._cond.notify()
        pending.event.wait()
        if pending.error is not None:
            raise pending.error
        return pending.result

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=30)
        # Fail anything still queued.  Drained under the lock so a batcher
        # thread that outlived the join timeout (e.g. blocked in a cold
        # compile) cannot race the deque; items it already popped are its to
        # complete, items still queued are ours to fail.
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
        for p in leftovers:
            p.error = InferenceServerException("server shutdown", status="500")
            p.event.set()

    # -- batcher thread ----------------------------------------------------

    def _loop(self):
        try:
            self._run()
        except BaseException:  # noqa: BLE001 - a dead batcher must not strand waiters
            with self._cond:
                self._closed = True
                leftovers = list(self._queue) + [
                    p for p in self._active if not p.event.is_set()
                ]
                self._queue.clear()
                self._active.clear()
            err = InferenceServerException(
                f"model '{self.model.name}' batcher thread died", status="500"
            )
            for p in leftovers:
                p.error = err
                p.event.set()
            raise

    def _run(self):
        # Depth-2 pipeline: dispatch batch K+1 (host concat + async H2D +
        # async forward) BEFORE blocking on batch K's D2H, so the host->device
        # link streams the next batch while the previous one drains.  On a
        # remote/tunneled chip this is the difference between serial
        # (gather, transfer, wait) x N and a saturated link.
        inflight = None
        while True:
            group = self._gather()
            if group is None:
                if inflight is not None:
                    self._complete(*inflight)
                return
            dispatched = self._dispatch(group)
            if inflight is not None:
                self._complete(*inflight)
            inflight = dispatched
            if inflight is None:
                continue
            # If the queue is empty, finish the in-flight batch now instead of
            # holding its requesters hostage to the next arrival.
            with self._cond:
                empty = not self._queue
            if empty:
                self._complete(*inflight)
                inflight = None

    def _gather(self):
        """Take the oldest request, then wait up to max_queue_delay for
        signature-compatible peers (or until the batch is full)."""
        with self._cond:
            while not self._queue:
                if self._closed:
                    return None
                self._cond.wait()
            first = self._queue.popleft()
            self._active.add(first)
            group = [first]
            rows = first.rows
            deadline = time.monotonic() + self.max_queue_delay_s
            while rows < self.max_batch:
                # drain compatible items already queued
                taken = False
                for i, p in enumerate(self._queue):
                    if p.signature == first.signature and rows + p.rows <= self.max_batch:
                        del self._queue[i]
                        self._active.add(p)
                        group.append(p)
                        rows += p.rows
                        taken = True
                        break
                if taken:
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._closed:
                    break
                self._cond.wait(timeout=remaining)
            return group

    def _dispatch(self, group):
        """Host-concat the group, pad to a power-of-two bucket, and issue the
        (asynchronous) forward.  Returns state for _complete, or None if the
        dispatch failed (the group is already notified).

        The engine duty-cycle span opens here and closes in _complete/_fail:
        the device is considered busy from issue until results land."""
        t0 = time.monotonic_ns()
        if self._busy is not None:
            self._busy.begin()
        try:
            device = group[0].signature[0]
            names = [name for name, _, _ in group[0].signature[1:]]
            rows = sum(p.rows for p in group)
            # rows <= max_batch by construction, so padded >= rows always.
            padded = _bucket(rows, cap=self.max_batch)
            if device:
                # TPU-shm path: concat + pad stay on device (one XLA op per
                # input); the forward runs at batch=`padded` on the MXU
                # instead of `len(group)` batch-1 dispatches.
                import jax.numpy as jnp

                concat = jnp.concatenate
                zeros = jnp.zeros
            else:
                concat, zeros = np.concatenate, np.zeros
            batched = {}
            for name in names:
                parts = [p.inputs[name] for p in group]
                if padded > rows:
                    pad_shape = (padded - rows,) + tuple(parts[0].shape[1:])
                    parts.append(zeros(pad_shape, dtype=parts[0].dtype))
                batched[name] = (
                    concat(parts, axis=0) if len(parts) > 1 else parts[0]
                )
            t_in = time.monotonic_ns()
            result = self.model.fn(batched, {}, None)
            return group, result, rows, t0, t_in
        except Exception as e:  # noqa: BLE001 - failure propagates per-request
            if self._busy is not None:
                self._busy.end()
            self._fail(group, e)
            return None

    def _complete(self, group, result, rows, t0, t_in):
        """Split rows back to requests and record stats.

        Wire groups block on one batch-wide D2H (device arrays would
        re-transfer per request); device groups split into live device slices
        — outputs flow into TPU-shm regions with no transfer at all, and the
        dispatch stays asynchronous."""
        busy_open = self._busy is not None
        try:
            if group[0].signature[0]:
                host = result  # device group: keep everything on device
            else:
                import jax

                host = jax.device_get(result)
            if busy_open:
                self._busy.end()  # results landed (or dispatch issued)
                busy_open = False
            t_inf = time.monotonic_ns()
            offset = 0
            for p in group:
                p.result = {
                    name: arr[offset : offset + p.rows] for name, arr in host.items()
                }
                offset += p.rows
                p.event.set()
            with self._cond:
                self._active.difference_update(group)
            t1 = time.monotonic_ns()
            queue_ns = sum(t_in - p.t_enq for p in group)
            self.stats.record_batched(
                rows=rows,
                infer_ns=t_inf - t_in,
                input_ns=t_in - t0,
                output_ns=t1 - t_inf,
                queue_ns=queue_ns,
            )
        except Exception as e:  # noqa: BLE001 - failure propagates per-request
            if busy_open:
                self._busy.end()  # device_get raised before the span closed
            self._fail(group, e)

    def _fail(self, group, e):
        err = (
            e
            if isinstance(e, InferenceServerException)
            else InferenceServerException(
                f"{self.model.name}: batched execution failed: {e}",
                status="500",
                debug_details=e,
            )
        )
        for p in group:
            p.error = err
            p.event.set()
        with self._cond:
            self._active.difference_update(group)


def _leading_rows(inputs):
    for arr in inputs.values():
        if arr.ndim == 0:
            raise InferenceServerException(
                "batchable model input must have a leading batch dimension",
                status="400",
            )
        return int(arr.shape[0])
    raise InferenceServerException("request has no inputs", status="400")


def batchable_request(model, inputs, params, context, request):
    """Whether this request may take the dynamic-batching path."""
    if not model.dynamic_batching or model.decoupled or model.stateful:
        return False
    if context is not None or params.get("sequence_id"):
        return False
    # Request parameters beyond rendering hints reach model.fn on the direct
    # path; the batcher calls fn once for many requests and cannot honor
    # per-request parameters, so any such request keeps the direct path.
    if any(k not in ("binary_data_output",) for k in params):
        return False
    if model.max_batch_size <= 1:
        return False
    device = bool(inputs) and all(
        _is_device_array(a) for a in inputs.values()
    )
    if not device:
        for out in request.get("outputs") or []:
            # shm outputs of HOST groups stay on the direct path: host-mode
            # batching materializes outputs host-side, which would cost the
            # shm path its zero-copy write.  Device groups render outputs as
            # live device slices, so shm outputs batch fine there.
            if "shared_memory_region" in (out.get("parameters") or {}):
                return False
    rows = None
    for arr in inputs.values():
        if isinstance(arr, np.ndarray):
            if arr.dtype == np.object_:
                return False  # BYTES inputs: direct path
        elif not _is_device_array(arr):
            return False
        if arr.ndim == 0:
            return False
        if rows is None:
            rows = arr.shape[0]
        elif arr.shape[0] != rows:
            return False
    # mixed host/device inputs in one request keep the direct path (a device
    # concat would silently D2H the host parts or vice versa)
    if not device and any(_is_device_array(a) for a in inputs.values()):
        return False
    return rows is not None and rows <= model.max_batch_size
