"""Cross-request dynamic micro-batching for stateless batchable models.

TPU-first rationale: the MXU wants large batched matmuls/convs, and every
device round trip (H2D, dispatch, D2H) carries fixed latency — per-request
execution pays that latency per request, a batcher pays it per *batch*.  This
is the server-side analog of the dynamic batcher in the reference's server
ecosystem (the client-side reference exposes it via model config
``dynamic_batching``; model_parser.h:59-193 normalizes scheduler kinds), built
the XLA way: batches are padded to power-of-two buckets so every batch size
hits an already-compiled executable instead of triggering a retrace.

Eligibility: stateless, non-decoupled models with ``max_batch_size > 1`` and
host-resident (wire) inputs.  Shared-memory requests keep the direct
zero-copy path — batching them would force device→host materialization.
"""

import sys
import threading
import time
from collections import deque

import numpy as np

from client_tpu.serve._completion import CompletionObserver
from client_tpu.utils import InferenceServerException


def _bucket(n, cap):
    """Smallest bucket >= n from {2^k, 3*2^k}, capped at cap.

    The 1.5x intermediate sizes keep worst-case padding waste to 33% instead
    of 100% while the bucket count (and so the compile count) stays O(log n).
    """
    b = 1
    while b < n:
        if b * 3 // 2 >= n and b >= 2:
            b = b * 3 // 2
            break
        b *= 2
    return min(b, cap)


def _buckets_up_to(cap):
    """All bucket sizes warmup must cover, ending exactly at cap."""
    out = []
    b = 1
    while b < cap:
        out.append(b)
        if b >= 2 and b * 3 // 2 < cap:
            out.append(b * 3 // 2)
        b *= 2
    out.append(cap)
    return sorted(set(out))


def _is_device_array(arr):
    """jax.Array check without importing jax on the host-only path."""
    jax = sys.modules.get("jax")
    return jax is not None and isinstance(arr, jax.Array)


def _device_batch(parts, padded):
    """Assemble a padded device batch with a *bounded executable set*.

    ``jnp.concatenate`` over a variable part count compiles one executable per
    (arity, row-split) combination, and on a remote/tunneled TPU every new
    executable costs seconds — measured ~4.5s per shape on the axon tunnel,
    which is exactly the rows-vary-per-window pathology dynamic batching
    creates.  Instead: allocate the pre-zeroed bucket buffer (one executable
    per bucket; the zero fill doubles as padding) and lay each part in with
    ``dynamic_update_slice`` at a *runtime* offset — one executable per
    (bucket, part-row-count), independent of group composition, all covered
    by warmup.
    """
    from jax import lax

    import jax.numpy as jnp

    buf = jnp.zeros((padded,) + tuple(parts[0].shape[1:]), parts[0].dtype)
    zero_tail = (0,) * (parts[0].ndim - 1)
    offset = 0
    for p in parts:
        buf = lax.dynamic_update_slice(buf, p, (offset,) + zero_tail)
        offset += int(p.shape[0])
    return buf


def _fused_group_fn(model_fn):
    """One jitted callable serving every device-group composition: concat the
    parts, run the forward, split the outputs back per part — inside a single
    XLA program, so a K-request group costs exactly ONE dispatch and zero
    per-request eager ops.  jax.jit retraces per (arity, row-split) pytree —
    single-row parts (the perf-client shape) dominate, so the executable set
    stays tiny and warmup covers it.  Requires a jax-pure model fn
    (``Model.fused_batching``)."""
    import jax

    def fused(parts):
        import jax.numpy as jnp

        batched = {
            name: jnp.concatenate(list(ps), axis=0) if len(ps) > 1 else ps[0]
            for name, ps in parts.items()
        }
        out = model_fn(batched, {}, None)
        # reserved response-params key: a traced fn's dict would be a
        # trace-time constant (stale across calls) and jnp.split chokes on
        # it — fused models cannot set per-response parameters; drop it
        if isinstance(out, dict):
            out.pop("__parameters__", None)
        sizes = [int(p.shape[0]) for p in next(iter(parts.values()))]
        offs = list(np.cumsum(sizes[:-1]))
        return {
            name: tuple(jnp.split(arr, offs, axis=0)) if offs else (arr,)
            for name, arr in out.items()
        }

    return jax.jit(fused)


def _device_split(arr, offset, rows):
    """One request's row slice, executable set bounded per (shape, rows):
    ``dynamic_slice`` with a runtime offset — basic ``arr[a:b]`` slicing
    would compile one executable per distinct offset."""
    from jax import lax

    sizes = (rows,) + tuple(arr.shape[1:])
    return lax.dynamic_slice(arr, (offset,) + (0,) * (arr.ndim - 1), sizes)


class _Pending:
    __slots__ = ("inputs", "rows", "signature", "event", "result", "error",
                 "t_enq", "trace", "tenant", "weight", "vfinish")

    def __init__(self, inputs, rows, signature, trace=None, tenant="",
                 weight=1.0):
        self.inputs = inputs
        self.rows = rows
        self.signature = signature
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.t_enq = time.monotonic_ns()
        self.trace = trace  # optional RequestTrace (queue/compute events)
        self.tenant = tenant  # fair-queue lane (see _FairQueue)
        self.weight = max(float(weight), 1e-3)
        self.vfinish = 0.0  # virtual finish time, stamped at push


class _FairQueue:
    """Weighted-fair queue over per-tenant FIFO lanes.

    The batcher's old single FIFO serves a flooding tenant's backlog ahead
    of everyone who arrived later — arrival order IS the schedule.  Here
    each request is stamped a *virtual finish time* on push
    (``max(vclock, lane_last_finish) + rows / weight``, the classic
    start-time fair queueing recurrence) and :meth:`pop` always takes the
    earliest stamp across lane heads: a tenant's burst deepens only its
    own lane, and service converges to the weight ratio regardless of
    arrival order.  Within one lane order stays FIFO.

    Not internally locked — the batcher's ``_cond`` guards every call.
    """

    __slots__ = ("_lanes", "_last_vfinish", "_vclock", "_len")

    def __init__(self):
        self._lanes = {}  # tenant -> deque of _Pending
        self._last_vfinish = {}  # tenant -> last stamped vfinish
        self._vclock = 0.0
        self._len = 0

    def __len__(self):
        return self._len

    def push(self, pending):
        lane = self._lanes.get(pending.tenant)
        if lane is None:
            lane = deque()
            self._lanes[pending.tenant] = lane
        start = max(
            self._vclock, self._last_vfinish.get(pending.tenant, 0.0)
        )
        pending.vfinish = start + max(pending.rows, 1) / pending.weight
        self._last_vfinish[pending.tenant] = pending.vfinish
        lane.append(pending)
        self._len += 1

    def pop(self):
        """Remove and return the entry with the earliest virtual finish
        time (caller guarantees non-empty)."""
        best_tenant, best = None, None
        for tenant, lane in self._lanes.items():
            head = lane[0]
            if best is None or head.vfinish < best.vfinish:
                best_tenant, best = tenant, head
        self._remove(best_tenant, 0)
        self._vclock = max(self._vclock, best.vfinish)
        return best

    def take_first(self, pred):
        """Remove and return the fair-order-first entry matching *pred*
        (the batch fold-in scan), or None.  Per lane only the earliest
        match is a candidate — lane order stays FIFO."""
        best_tenant, best_i, best = None, None, None
        for tenant, lane in self._lanes.items():
            for i, pending in enumerate(lane):
                if pred(pending):
                    if best is None or pending.vfinish < best.vfinish:
                        best_tenant, best_i, best = tenant, i, pending
                    break
        if best is None:
            return None
        self._remove(best_tenant, best_i)
        return best

    def _remove(self, tenant, index):
        lane = self._lanes[tenant]
        del lane[index]
        self._len -= 1
        if not lane:
            del self._lanes[tenant]
        if not self._lanes:
            # busy period over: forget per-tenant stamps so the map cannot
            # grow without bound across tenant churn (vclock memory only
            # matters while requests are queued)
            self._last_vfinish.clear()
            self._vclock = 0.0

    def depths(self):
        """{tenant: queued count} (/metrics per-tenant queue gauge)."""
        return {tenant: len(lane) for tenant, lane in self._lanes.items()}

    def drain(self):
        """Remove and return every queued entry (shutdown/failure paths)."""
        out = [p for lane in self._lanes.values() for p in lane]
        self._lanes.clear()
        self._last_vfinish.clear()
        self._vclock = 0.0
        self._len = 0
        return out


class ModelBatcher:
    """One background batcher per model: gathers concurrent requests into a
    single padded forward pass and splits the host-materialized outputs."""

    def __init__(self, model, stats, max_queue_delay_s=0.003, busy=None,
                 pipeline_depth=4, max_queue_depth=None, registry=None,
                 prof=None):
        self.model = model
        self.stats = stats
        self._busy = busy  # engine BusyTracker (duty-cycle metric), optional
        self._registry = registry  # engine metrics Registry (shed counters)
        self.prof = prof  # engine PhaseProfiler: one "batch" tick per group
        self.max_batch = max(int(model.max_batch_size), 1)
        self.max_queue_delay_s = max_queue_delay_s
        # Admission control: requests beyond this queue depth are shed with
        # a retryable 503 instead of growing the queue (and the tail
        # latency) without bound.  None = unbounded.
        self.max_queue_depth = max_queue_depth
        # Device groups with a jax-pure fn fuse concat+forward+split into ONE
        # jitted dispatch (see _fused_jit); arity is capped so the executable
        # set stays warmable.
        self._fused = None
        self.max_fused_arity = int(
            getattr(model, "max_fused_arity", 8) or 8
        )
        # Dispatch/completion are decoupled: the batcher thread only gathers
        # and issues batches; completion waits run off the dispatch path.  On
        # a remote/tunneled chip a completion wait costs a full link RTT —
        # serializing it behind dispatch (the old depth-2 pipeline) left the
        # H2D stream idle ~half the time.  Two populations, two backpressure
        # regimes:
        #  - HOST (wire) groups hold full tensor copies host-side and end in
        #    a real batch-wide D2H, so a small completion pool + semaphore
        #    (pipeline_depth) bounds memory while keeping the link streaming.
        #  - DEVICE (TPU-shm) groups hold only HBM references; acks are
        #    dispatch-time by contract, so throttling dispatch to the
        #    completion-OBSERVATION rate (RTT-quantized over a tunnel) would
        #    cap throughput at depth/RTT.  They get a deep semaphore purely
        #    as a runaway bound, and one FIFO watcher thread that collapses a
        #    completion backlog into a single block_until_ready (a device
        #    stream executes dispatches in order, so the newest result
        #    completing implies every older one did).
        self.pipeline_depth = max(int(pipeline_depth), 1)
        self.device_pipeline_depth = max(self.pipeline_depth, 64)
        self._sem = threading.Semaphore(self.pipeline_depth)
        self._sem_device = threading.Semaphore(self.device_pipeline_depth)
        self._observer = CompletionObserver(
            name=f"batcher-{model.name}-watch"
        )
        # Host completions run real work (batch D2H + row split) on daemon
        # worker threads consuming _host_q; daemon so a wedged device call
        # can never hang interpreter exit, bounded-waited in close().
        self._host_cv = threading.Condition()
        self._host_q = deque()
        self._host_threads = []
        self._host_outstanding = 0
        # Workers exit on _host_closed, set only AFTER the batcher thread is
        # joined: the batcher keeps dispatching its remaining queue after
        # _closed, and a worker exiting early on a momentarily-empty queue
        # would strand those late batches (clients blocked forever).
        self._host_closed = False
        self._inflight = 0  # dispatched, completion pending (under _cond)
        self._cond = threading.Condition()
        # Weighted-fair queue across tenant lanes (one lane per tenant;
        # submit() stamps tenant + weight) — replaces the single FIFO so a
        # flooding tenant's backlog cannot schedule ahead of everyone else.
        self._queue = _FairQueue()
        # Requests popped off the queue but not yet completed/failed (gathered
        # group + the in-flight pipelined batch).  Tracked so the _loop
        # BaseException handler can fail them too — otherwise a KeyboardInterrupt
        # /MemoryError between _gather and _fail strands those waiters forever.
        self._active = set()
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name=f"batcher-{model.name}", daemon=True
        )
        self._thread.start()

    def _use_fused(self):
        return bool(getattr(self.model, "fused_batching", False))

    def _fused_jit(self):
        # memoized under _cond: warmup (caller thread) and the batcher
        # loop both reach this — an unguarded rebind races them
        with self._cond:
            if self._fused is None:
                self._fused = _fused_group_fn(self.model.fn)
            return self._fused

    def warmup(self, input_specs):
        """Pre-compile every padded bucket (the reference's ``model_warmup``
        analog) so no client request ever pays a compile.  Covers both group
        populations: the wire path (host-array forward per bucket) and the
        device/TPU-shm path (bucket-buffer assembly from single-row parts,
        forward, and per-request output split) — on a tunneled chip an
        unwarmed executable costs seconds at request time.  Skipped for
        models with dynamic non-batch dims."""
        from client_tpu.utils import triton_to_np_dtype

        shapes = {}
        for spec in input_specs:
            dims = list(spec.dims)
            if any(d < 0 for d in dims[1:]):
                return
            np_dtype = triton_to_np_dtype(spec.datatype)
            if np_dtype is None or np_dtype == np.object_:
                return
            shapes[spec.name] = (dims[1:], np_dtype)
        buckets = _buckets_up_to(self.max_batch)
        import jax

        for b in buckets:
            zeros = {
                name: np.zeros([b] + dims, dtype=np_dtype)
                for name, (dims, np_dtype) in shapes.items()
            }
            jax.device_get(self.model.fn(zeros, {}, None))
        if not getattr(self.model, "batch_device_inputs", False):
            return
        # Device-group pass: single-row parts are what concurrent perf
        # clients send.  The rows are committed to the device explicitly —
        # TPU-shm region arrays arrive committed, and committedness is part
        # of the jit cache key: an uncommitted warmup would leave every
        # serving-time signature cold (retrace + executable reload).
        dev = jax.devices()[0]
        row = {
            name: jax.device_put(np.zeros([1] + dims, dtype=np_dtype), dev)
            for name, (dims, np_dtype) in shapes.items()
        }
        if self._use_fused():
            # one compile per (arity, part-rows): groups of k single-row
            # requests (the concurrency-sweep shape) plus k-part groups of
            # the batched-client row sizes (reference perf_analyzer -b
            # 8/32).  Larger rows cap arity at max_batch//rows, so the
            # extra row sizes add only a handful of executables.
            for rows in (1, 8, 32):
                if rows > self.max_batch:
                    continue
                part = {
                    name: jax.device_put(
                        np.zeros([rows] + dims, dtype=np_dtype), dev
                    )
                    for name, (dims, np_dtype) in shapes.items()
                }
                max_k = min(self.max_fused_arity, self.max_batch // rows)
                for k in range(1, max_k + 1):
                    parts = {name: (p,) * k for name, p in part.items()}
                    out = self._fused_jit()(parts)
                    jax.block_until_ready(out)
            return
        # eager assembly path: per bucket warm (zeros-buffer + one-row
        # dynamic_update_slice) assembly, the forward on an assembled
        # buffer, and the one-row output split.
        for b in buckets:
            batched = {
                name: _device_batch([part], b) for name, part in row.items()
            }
            result = self.model.fn(batched, {}, None)
            for arr in result.values():
                if _is_device_array(arr) and arr.shape and arr.shape[0] == b:
                    _device_split(arr, 0, 1).block_until_ready()

    # -- request side -----------------------------------------------------

    def queue_depth(self):
        """Requests currently waiting in the queue (/metrics gauge)."""
        with self._cond:
            return len(self._queue)

    def queue_depths_by_tenant(self):
        """{tenant: queued count} (/metrics per-tenant queue gauge)."""
        with self._cond:
            return self._queue.depths()

    def submit(self, inputs, trace=None, tenant="", weight=1.0):
        """Block until the batched execution finishes; return this request's
        slice of the outputs — host numpy arrays for wire groups, live device
        slices for device (TPU-shm) groups.  ``tenant``/``weight`` select
        and weight the fair-queue lane this request waits in."""
        rows = _leading_rows(inputs)
        # Device-resident requests batch with the jnp path (concat + split on
        # device, no transfers) and must never mix with host groups — the
        # signature's device flag keeps the populations apart.
        device = all(_is_device_array(a) for a in inputs.values())
        signature = (device,) + tuple(
            (name, arr.dtype.str, tuple(arr.shape[1:]))
            for name, arr in sorted(inputs.items())
        )
        if device and self._use_fused():
            # fused jit retraces per (arity, row-split): mixing row counts in
            # one group would hit signatures warmup never compiled (seconds
            # of cold XLA compile on the request path) — groups stay
            # row-uniform so every composition is a warmed executable
            signature += (rows,)
        pending = _Pending(inputs, rows, signature, trace, tenant=tenant,
                           weight=weight)
        with self._cond:
            if self._closed:
                raise InferenceServerException(
                    f"model '{self.model.name}' is shutting down", status="500"
                )
            if (
                self.max_queue_depth is not None
                and len(self._queue) >= self.max_queue_depth
            ):
                # Retryable overload: the client's retry policy backs off
                # and re-submits once the queue drains (503 == UNAVAILABLE
                # on the gRPC frontend).
                # one consistent label set across the family: the _admit
                # sheds (overload/draining) carry only {reason}, so no
                # model label here either — a by-model aggregation would
                # silently split the family otherwise
                if self._registry is not None:
                    self._registry.inc(
                        "ctpu_requests_shed_total",
                        {"reason": "queue_full"},
                        help_="Requests shed with a retryable 503",
                    )
                raise InferenceServerException(
                    f"model '{self.model.name}' queue is full "
                    f"({len(self._queue)} >= {self.max_queue_depth} queued); "
                    "retry after backoff",
                    status="503",
                )
            self._queue.push(pending)
            self._cond.notify()
        pending.event.wait()
        if pending.error is not None:
            raise pending.error
        return pending.result

    def close(self, shutdown_timeout_s=30.0):
        # One deadline budget shared across every shutdown phase (batcher
        # join, host-completion drain, observer close) — three independent
        # 30s waits made worst-case close() take 90s; the caller's budget
        # now bounds the whole shutdown.
        deadline = time.monotonic() + shutdown_timeout_s
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=max(deadline - time.monotonic(), 0.0))
        # Host completion tasks for batches already dispatched should finish
        # before leftovers are failed — their requests are _active, not
        # queued.  Bounded: a task wedged on a stalled device must not hang
        # close() (the workers are daemon threads; queued requests still get
        # their shutdown error below).
        with self._host_cv:
            self._host_closed = True
            self._host_cv.notify_all()
            while self._host_outstanding or self._host_q:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._host_cv.wait(timeout=remaining)
        self._observer.close(timeout=max(deadline - time.monotonic(), 0.0))
        # Fail anything still queued.  Drained under the lock so a batcher
        # thread that outlived the join timeout (e.g. blocked in a cold
        # compile) cannot race the queue; items it already popped are its to
        # complete, items still queued are ours to fail.
        with self._cond:
            leftovers = self._queue.drain()
        for p in leftovers:
            p.error = InferenceServerException("server shutdown", status="500")
            p.event.set()

    # -- batcher thread ----------------------------------------------------

    def _loop(self):
        try:
            self._run()
        except BaseException:  # noqa: BLE001 - a dead batcher must not strand waiters
            with self._cond:
                self._closed = True
                leftovers = self._queue.drain() + [
                    p for p in self._active if not p.event.is_set()
                ]
                self._active.clear()
            err = InferenceServerException(
                f"model '{self.model.name}' batcher thread died", status="500"
            )
            for p in leftovers:
                p.error = err
                p.event.set()
            raise

    def _run(self):
        # Pipelined dispatch: the batcher thread gathers and issues batches;
        # completion waits run elsewhere (pool for host groups, FIFO watcher
        # for device groups), so on a remote/tunneled chip the H2D stream
        # keeps flowing while earlier batches' completion RTTs are in flight.
        while True:
            group = self._gather()
            if group is None:
                return
            device = group[0].signature[0]
            sem = self._sem_device if device else self._sem
            # Backpressure: block while the pipeline is full.  The queue
            # keeps filling meanwhile, and _topup folds those arrivals into
            # this batch — depth and batch size grow together under load.
            sem.acquire()
            self._topup(group)
            dispatched = self._dispatch(group)
            if dispatched is None:
                sem.release()
                continue
            with self._cond:
                self._inflight += 1
            if device:
                arrays = self._handoff_device(*dispatched)
                if arrays is None:  # handoff failed; group already notified
                    if self._busy is not None:
                        self._busy.end()
                    self._finish_one(sem)
                else:
                    self._observer.watch(
                        arrays, lambda s=sem: self._device_done(s)
                    )
            else:
                self._submit_host(dispatched)

    def _device_done(self, sem):
        """Observer callback: a device batch's results actually landed."""
        if self._busy is not None:
            self._busy.end()
        self._finish_one(sem)

    def _finish_one(self, sem):
        with self._cond:
            self._inflight -= 1
            # wake a _gather waiting out its peer-delay: with nothing in
            # flight the delay no longer buys anything
            self._cond.notify_all()
        sem.release()

    # -- host-group completion workers --------------------------------------

    def _submit_host(self, dispatched):
        with self._host_cv:
            self._host_q.append(dispatched)
            self._host_threads = [
                t for t in self._host_threads if t.is_alive()
            ]
            if len(self._host_threads) < self.pipeline_depth:
                t = threading.Thread(
                    target=self._host_loop,
                    name=f"batcher-{self.model.name}-done",
                    daemon=True,
                )
                self._host_threads.append(t)
                t.start()
            self._host_cv.notify()

    def _host_loop(self):
        # one guard per pass (the BG-THREAD-CRASH shape): an escaped
        # exception would kill this completion worker silently and
        # strand every group queued behind it
        while True:
            try:
                if not self._host_once():
                    return
            except Exception:
                pass

    def _host_once(self):
        """Complete one dispatched host group; False once closed and
        drained (the outstanding/semaphore accounting is exception-safe
        either way)."""
        with self._host_cv:
            while not self._host_q and not self._host_closed:
                self._host_cv.wait()
            if not self._host_q:
                self._host_cv.notify_all()  # wake the close() waiter
                return False
            dispatched = self._host_q.popleft()
            self._host_outstanding += 1
        try:
            self._complete_host(*dispatched)
        finally:
            with self._host_cv:
                self._host_outstanding -= 1
                self._host_cv.notify_all()
            self._finish_one(self._sem)
        return True

    def _drain_compatible_locked(self, group, first, rows, max_arity):
        """Fold queued signature-compatible requests into *group* (no wait),
        taken in fair-queue order so the fold-in cannot become a side door
        around the weighted-fair schedule.  Caller holds self._cond.
        Returns the updated row count."""
        while rows < self.max_batch and len(group) < max_arity:
            taken = self._queue.take_first(
                lambda p, rows=rows: (
                    p.signature == first.signature
                    and rows + p.rows <= self.max_batch
                )
            )
            if taken is None:
                break
            self._active.add(taken)
            group.append(taken)
            rows += taken.rows
        return rows

    def _max_arity(self, first):
        # Fused device groups cap the part count so the (arity,
        # row-split)-keyed executable set stays small and warmable.
        return (
            self.max_fused_arity
            if first.signature[0] and self._use_fused()
            else self.max_batch
        )

    def _gather(self):
        """Take the oldest request and fold in signature-compatible peers.

        Batch-while-busy: the timed max_queue_delay wait for peers only
        happens while at least one batch is dispatched-but-incomplete — an
        idle pipeline dispatches immediately, so low-concurrency requests pay
        zero artificial queue delay (the reference's fixed-delay scheduler
        charges it unconditionally; this is the latency/throughput-optimal
        variant: delay only when the delay is hidden by in-flight work)."""
        with self._cond:
            while not self._queue:
                if self._closed:
                    return None
                self._cond.wait()
            first = self._queue.pop()
            self._active.add(first)
            group = [first]
            max_arity = self._max_arity(first)
            rows = self._drain_compatible_locked(
                group, first, first.rows, max_arity
            )
            deadline = time.monotonic() + self.max_queue_delay_s
            while (
                rows < self.max_batch
                and len(group) < max_arity
                and self._inflight > 0
                and not self._closed
            ):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(timeout=remaining)
                rows = self._drain_compatible_locked(
                    group, first, rows, max_arity
                )
            return group

    def _topup(self, group):
        """Last-moment fold-in of arrivals that queued while the pipeline
        semaphore blocked (or between gather and dispatch)."""
        with self._cond:
            first = group[0]
            rows = sum(p.rows for p in group)
            self._drain_compatible_locked(
                group, first, rows, self._max_arity(first)
            )

    def _prof_commit(self, rows, t0, t_in, infer_ns, output_ns):
        """Fold one completed group into the engine's continuous
        profiler (serve/prof.py) as a "batch" tick, reusing the
        timestamps record_batched already took.  Queue wait is omitted:
        it overlaps other groups' device time, so summing it would
        double-count the wall."""
        prof = self.prof
        if prof is None:
            return
        prof.commit(
            "batch",
            (t_in - t0 + infer_ns + output_ns) / 1e9,
            phases={
                "host": (t_in - t0) / 1e9,
                "compute": infer_ns / 1e9,
                "render": output_ns / 1e9,
            },
            model=self.model.name,
            items=rows,
            flops_per_item=self.model.flops_per_item,
        )

    def _dispatch(self, group):
        """Host-concat the group, pad to a power-of-two bucket, and issue the
        (asynchronous) forward.  Returns state for _complete, or None if the
        dispatch failed (the group is already notified).

        The engine duty-cycle span opens here and closes in _complete/_fail:
        the device is considered busy from issue until results land."""
        t0 = time.monotonic_ns()
        w_dispatch = time.time_ns()
        for p in group:
            if p.trace is not None:
                p.trace.event("QUEUE_END", w_dispatch)
                p.trace.event("COMPUTE_START", w_dispatch)
        if self._busy is not None:
            self._busy.begin()
        try:
            device = group[0].signature[0]
            # per-input entries only (a fused-device signature carries a
            # trailing row-count scalar for group row-uniformity)
            names = [
                e[0] for e in group[0].signature[1:] if isinstance(e, tuple)
            ]
            rows = sum(p.rows for p in group)
            if device and self._use_fused():
                parts = {
                    name: tuple(p.inputs[name] for p in group)
                    for name in names
                }
                result = self._fused_jit()(parts)
                return group, ("fused", result), rows, t0, time.monotonic_ns()
            # rows <= max_batch by construction, so padded >= rows always.
            padded = _bucket(rows, cap=self.max_batch)
            batched = {}
            for name in names:
                parts = [p.inputs[name] for p in group]
                if device:
                    # TPU-shm path: assembly stays on device and the forward
                    # runs at batch=`padded` on the MXU instead of
                    # `len(group)` batch-1 dispatches.  A lone full-bucket
                    # part skips assembly entirely (zero-copy).
                    if len(parts) == 1 and parts[0].shape[0] == padded:
                        batched[name] = parts[0]
                    else:
                        batched[name] = _device_batch(parts, padded)
                else:
                    if padded > rows:
                        pad = np.zeros(
                            (padded - rows,) + tuple(parts[0].shape[1:]),
                            dtype=parts[0].dtype,
                        )
                        parts = parts + [pad]
                    batched[name] = (
                        np.concatenate(parts, axis=0)
                        if len(parts) > 1
                        else parts[0]
                    )
            t_in = time.monotonic_ns()
            result = self.model.fn(batched, {}, None)
            return group, result, rows, t0, t_in
        except Exception as e:  # noqa: BLE001 - failure propagates per-request
            if self._busy is not None:
                self._busy.end()
            self._fail(group, e)
            return None

    def _handoff_device(self, group, result, rows, t0, t_in):
        """Hand a device group's results to its waiters at DISPATCH time
        (ack == dispatch, the TPU-shm contract) — splitting is lazy device
        ops, no transfer.  Returns the arrays the watcher should observe for
        completion (busy span + semaphore close there), or None on failure
        (the group is already notified)."""
        try:
            w_done = time.time_ns()
            if isinstance(result, tuple) and result[0] == "fused":
                # per-part output arrays came straight out of the jitted
                # dispatch — hand them over, nothing left to do on host
                per_part = result[1]
                for i, p in enumerate(group):
                    p.result = {
                        name: parts[i] for name, parts in per_part.items()
                    }
                    # trace events land BEFORE the waiter wakes: the request
                    # thread completes/exports the trace as soon as it runs
                    if p.trace is not None:
                        p.trace.event("COMPUTE_END", w_done)
                    p.event.set()
                watch = per_part
            else:
                # batch-wide response parameters replicate, never slice
                # (reserved "__parameters__" result key)
                extra_params = (
                    result.pop("__parameters__", None)
                    if isinstance(result, dict)
                    else None
                )
                offset = 0
                for p in group:
                    # whole-buffer pass-through when one request fills the
                    # bucket; dynamic_slice otherwise (bounded executables)
                    p.result = {
                        name: arr
                        if offset == 0 and p.rows == arr.shape[0]
                        else _device_split(arr, offset, p.rows)
                        for name, arr in result.items()
                    }
                    if extra_params is not None:
                        p.result["__parameters__"] = extra_params
                    offset += p.rows
                    if p.trace is not None:
                        p.trace.event("COMPUTE_END", w_done)
                    p.event.set()
                watch = result
            with self._cond:
                self._active.difference_update(group)
            t1 = time.monotonic_ns()
            self.stats.record_batched(
                rows=rows,
                infer_ns=t1 - t_in,
                input_ns=t_in - t0,
                output_ns=0,
                queue_ns=sum(t_in - p.t_enq for p in group),
                queue_ns_each=[t_in - p.t_enq for p in group],
            )
            self._prof_commit(rows, t0, t_in, t1 - t_in, 0)
            return watch
        except Exception as e:  # noqa: BLE001 - failure propagates per-request
            self._fail(group, e)
            return None

    def _complete_host(self, group, result, rows, t0, t_in):
        """Wire-group completion (runs on the completion pool): one
        batch-wide D2H, then split host rows back to requests.  The busy
        span closes when results land host-side — real completion."""
        busy_open = self._busy is not None
        try:
            import jax

            host = jax.device_get(result)
            if busy_open:
                self._busy.end()  # wire results landed host-side
                busy_open = False
            t_inf = time.monotonic_ns()
            # response-level parameters (reserved "__parameters__" result
            # key) are batch-wide, not row-sliceable: replicate them onto
            # every request's split instead of slicing a dict
            extra_params = host.pop("__parameters__", None)
            w_done = time.time_ns()
            offset = 0
            for p in group:
                p.result = {
                    name: arr[offset : offset + p.rows]
                    for name, arr in host.items()
                }
                if extra_params is not None:
                    p.result["__parameters__"] = extra_params
                offset += p.rows
                if p.trace is not None:
                    p.trace.event("COMPUTE_END", w_done)
                p.event.set()
            with self._cond:
                self._active.difference_update(group)
            t1 = time.monotonic_ns()
            queue_ns = sum(t_in - p.t_enq for p in group)
            self.stats.record_batched(
                rows=rows,
                infer_ns=t_inf - t_in,
                input_ns=t_in - t0,
                output_ns=t1 - t_inf,
                queue_ns=queue_ns,
                queue_ns_each=[t_in - p.t_enq for p in group],
            )
            self._prof_commit(rows, t0, t_in, t_inf - t_in, t1 - t_inf)
        except Exception as e:  # noqa: BLE001 - failure propagates per-request
            if busy_open:
                self._busy.end()  # device_get raised before the span closed
            self._fail(group, e)

    def _fail(self, group, e):
        err = (
            e
            if isinstance(e, InferenceServerException)
            else InferenceServerException(
                f"{self.model.name}: batched execution failed: {e}",
                status="500",
                debug_details=e,
            )
        )
        for p in group:
            p.error = err
            p.event.set()
        with self._cond:
            self._active.difference_update(group)


def _leading_rows(inputs):
    for arr in inputs.values():
        if arr.ndim == 0:
            raise InferenceServerException(
                "batchable model input must have a leading batch dimension",
                status="400",
            )
        return int(arr.shape[0])
    raise InferenceServerException("request has no inputs", status="400")


def batchable_request(model, inputs, params, context, request):
    """Whether this request may take the dynamic-batching path."""
    if not model.dynamic_batching or model.decoupled or model.stateful:
        return False
    if context is not None or params.get("sequence_id"):
        return False
    # Request parameters beyond rendering hints reach model.fn on the direct
    # path; the batcher calls fn once for many requests and cannot honor
    # per-request parameters, so any such request keeps the direct path.
    if any(k not in ("binary_data_output",) for k in params):
        return False
    if model.max_batch_size <= 1:
        return False
    device = bool(inputs) and all(
        _is_device_array(a) for a in inputs.values()
    )
    if device and not getattr(model, "batch_device_inputs", False):
        # Device-resident (TPU-shm) inputs skip batching by default: the
        # forward dispatches on them directly (zero-copy, one async op),
        # while fusing adds assemble/split device ops per request — pure
        # overhead on a path that pays no H2D either way.  Batching exists
        # to amortize host<->device transfers; device arrays already did.
        # Opt in per model (`batch_device_inputs=True`) where per-dispatch
        # latency is negligible and MXU utilization dominates (chip-local
        # serving of compute-heavy models).
        return False
    if not device:
        for out in request.get("outputs") or []:
            # shm outputs of HOST groups stay on the direct path: host-mode
            # batching materializes outputs host-side, which would cost the
            # shm path its zero-copy write.  Device groups render outputs as
            # live device slices, so shm outputs batch fine there.
            if "shared_memory_region" in (out.get("parameters") or {}):
                return False
    rows = None
    for arr in inputs.values():
        if isinstance(arr, np.ndarray):
            if arr.dtype == np.object_:
                return False  # BYTES inputs: direct path
        elif not _is_device_array(arr):
            return False
        if arr.ndim == 0:
            return False
        if rows is None:
            rows = arr.shape[0]
        elif arr.shape[0] != rows:
            return False
    # mixed host/device inputs in one request keep the direct path (a device
    # concat would silently D2H the host parts or vice versa)
    if not device and any(_is_device_array(a) for a in inputs.values()):
        return False
    return rows is not None and rows <= model.max_batch_size
