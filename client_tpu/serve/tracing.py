"""Server-side request tracing: per-request timelines behind trace_settings.

The server half of the end-to-end tracing subsystem (the client half —
traceparent generation and client spans — lives in ``client_tpu.tracing``).
The engine owns one :class:`Tracer`; the HTTP/gRPC frontends sample a
:class:`RequestTrace` per inference request (joining the client's trace id
when a ``traceparent`` header/metadata entry arrives) and the engine and
dynamic batcher record the timeline:

    REQUEST_START -> QUEUE_START -> QUEUE_END -> COMPUTE_START ->
    COMPUTE_INPUT_END -> COMPUTE_OUTPUT_START -> COMPUTE_END ->
    RESPONSE_SENT

(the timestamp names Triton's trace API emits for its queue/compute
breakdown; batched requests get their QUEUE_END/COMPUTE_* from the
batcher at dispatch/completion time).

Sampling honors the engine's ``trace_settings`` exactly as the reference
trace extension defines them: ``trace_level`` ([\"OFF\"] disables),
``trace_rate`` (trace the first of every N requests), ``trace_count``
(budget of traces, -1 unlimited, resets when updated), ``trace_file``
(JSON-lines export, one Triton-shaped record per trace) and
``log_frequency`` (buffer N records between file flushes; 0 flushes per
trace).
"""

import collections
import contextlib
import threading
import time

from client_tpu.tracing import (
    append_trace_record,
    format_traceparent,
    gen_span_id,
    gen_trace_id,
    parse_traceparent,
)
from client_tpu.tracing import ClientTrace as _SpanBase
from client_tpu.utils import InferenceServerException

__all__ = [
    "RequestTrace",
    "Tracer",
    "TRACE_SETTING_DEFAULTS",
    "current_trace",
    "normalize_trace_settings",
    "push_trace",
]

# The request trace active on THIS thread (the engine brackets execute()
# with push_trace).  The fleet tier reads it so a peer RPC issued while
# serving a traced request records a child span under the request's trace
# id — no plumbing of the trace object through every call layer.
_ACTIVE = threading.local()


def current_trace():
    """The RequestTrace the current thread is serving, or None."""
    return getattr(_ACTIVE, "trace", None)


@contextlib.contextmanager
def push_trace(trace):
    """Install *trace* (may be None) as this thread's active request
    trace for the duration of the block; always restores the previous
    one (nested ensemble steps re-enter the engine on the same thread)."""
    prev = getattr(_ACTIVE, "trace", None)
    _ACTIVE.trace = trace
    try:
        yield trace
    finally:
        _ACTIVE.trace = prev

TRACE_LEVELS = ("OFF", "TIMESTAMPS", "TENSORS")

TRACE_SETTING_DEFAULTS = {
    "trace_file": "",
    "trace_level": ["OFF"],
    "trace_rate": "1000",
    "trace_count": "-1",
    "log_frequency": "0",
}

_INT_KEYS = ("trace_rate", "trace_count", "log_frequency")


def normalize_trace_settings(updates):
    """Canonicalize a trace-settings update to the wire schema both
    protocols round-trip: ``trace_level`` is a list of level names,
    every numeric setting is the decimal *string* of an int, and
    ``trace_file`` is a string.  Raises a 400 on malformed values so a
    bad update is rejected rather than half-applied."""
    normalized = {}
    for key, value in (updates or {}).items():
        if value is None:
            continue  # present-but-empty: leave the current value alone
        if key == "trace_level":
            levels = value if isinstance(value, (list, tuple)) else [value]
            levels = [str(lv).upper() for lv in levels]
            bad = [lv for lv in levels if lv not in TRACE_LEVELS]
            if bad or not levels:
                raise InferenceServerException(
                    f"invalid trace_level {bad or levels}: levels are "
                    f"{list(TRACE_LEVELS)}",
                    status="400",
                )
            normalized[key] = levels
        elif key in _INT_KEYS:
            if isinstance(value, (list, tuple)):
                value = value[0] if value else ""
            try:
                normalized[key] = str(int(str(value)))
            except ValueError:
                raise InferenceServerException(
                    f"invalid {key} {value!r}: expected an integer",
                    status="400",
                ) from None
        elif key == "trace_file":
            if isinstance(value, (list, tuple)):
                value = value[0] if value else ""
            normalized[key] = str(value)
        else:
            raise InferenceServerException(
                f"unknown trace setting {key!r}", status="400"
            )
    return normalized


class RequestTrace(_SpanBase):
    """One traced server-side request (a span joined to the client's
    trace id when the request carried a traceparent)."""

    def __init__(self, trace_id, span_id, parent_span_id=None,
                 model_name="", model_version="", protocol="", seq=0,
                 step="", ensemble=""):
        super().__init__(trace_id, span_id, model_name)
        self.parent_span_id = parent_span_id
        self.model_version = model_version
        self.protocol = protocol
        self.seq = seq
        # tenant identity (x-tenant-id header/metadata), stamped by the
        # engine so per-tenant latency can be split straight from traces
        self.tenant = ""
        # ensemble step tags (serve/pipeline.py): one child span per DAG
        # step, tagged with the step label and the owning ensemble so
        # branch overlap reads straight off the exported timeline
        self.step = step
        self.ensemble = ensemble
        # free-form key/value tags (peer url, bytes, breaker state,
        # hit/miss, resume provenance) — exported verbatim so traceview
        # can attribute time without parsing event names
        self.tags = {}

    def traceparent(self):
        return format_traceparent(self.trace_id, self.span_id)

    def to_json(self):
        record = {
            "id": self.seq,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "source": "server",
            "protocol": self.protocol,
            "model_name": self.model_name,
            "model_version": self.model_version,
            "timestamps": list(self.timestamps),
        }
        if self.tenant:
            record["tenant"] = self.tenant
        if self.step:
            record["step"] = self.step
            record["composing_model"] = self.model_name
        if self.ensemble:
            record["ensemble"] = self.ensemble
        if self.tags:
            record["tags"] = dict(self.tags)
        if self.error:
            record["error"] = self.error
        return record


class Tracer:
    """Samples, collects, and exports per-request server traces.

    Reads the engine's live ``trace_settings`` dict on every sample so
    settings updates apply immediately; thread-safe (frontend handler
    threads sample concurrently)."""

    def __init__(self, settings, max_traces=1000):
        self._settings = settings  # the engine's live trace_settings dict
        self._lock = threading.Lock()
        self._seen = 0
        self._used = 0  # traces taken against the trace_count budget
        self._seq = 0
        self._pending_flush = []
        self.completed = collections.deque(maxlen=max_traces)
        # scheduler tick spans live apart from request traces: they fire
        # hundreds of times a second and must not evict request spans
        self._tick_seen = 0
        self.tick_completed = collections.deque(maxlen=max_traces)
        # fleet peer-RPC child spans (client side of prefix/cache/seq
        # lookups, durability pushes, anti-entropy) and the peer-server
        # side's serve spans — bounded apart from request spans for the
        # same reason as ticks
        self.peer_completed = collections.deque(maxlen=max_traces)
        # completion hook (the engine points it at the flight recorder so
        # every finished span lands in the postmortem ring even when no
        # trace_file is configured); called OUTSIDE the tracer lock
        self.on_complete = None

    def enabled(self):
        levels = self._settings.get("trace_level") or ["OFF"]
        return any(str(lv).upper() != "OFF" for lv in levels)

    def reset_budget(self):
        """Restart the trace_count budget (called when the setting is
        updated, matching the reference trace API's count semantics)."""
        with self._lock:
            self._used = 0

    @staticmethod
    def _int_setting(settings, key, default):
        try:
            return int(str(settings.get(key, default)))
        except (TypeError, ValueError):
            return default

    def sample(self, traceparent=None, model_name="", model_version="",
               protocol=""):
        """A RequestTrace for this request, or None (tracing off, request
        not sampled, or budget exhausted)."""
        if not self.enabled():
            return None
        rate = max(self._int_setting(self._settings, "trace_rate", 1), 1)
        count = self._int_setting(self._settings, "trace_count", -1)
        with self._lock:
            seen = self._seen
            self._seen += 1
            if seen % rate:
                return None
            if 0 <= count <= self._used:
                return None
            self._used += 1
            self._seq += 1
            seq = self._seq
        parent = parse_traceparent(traceparent)
        if parent is not None:
            trace_id, parent_span = parent
        else:
            trace_id, parent_span = gen_trace_id(), None
        return RequestTrace(
            trace_id, gen_span_id(), parent_span_id=parent_span,
            model_name=model_name, model_version=model_version,
            protocol=protocol, seq=seq,
        )

    def complete(self, trace):
        """Record a finished trace; export per log_frequency."""
        if trace is None:
            return
        self._complete_into(trace, self.completed)

    def _complete_into(self, trace, store):
        """Shared completion tail for request and tick spans: append to
        *store* and batch-flush to the trace file per log_frequency."""
        trace_file = self._settings.get("trace_file") or ""
        log_frequency = max(
            self._int_setting(self._settings, "log_frequency", 0), 0
        )
        to_write = []
        with self._lock:
            store.append(trace)
            if trace_file:
                self._pending_flush.append(trace.to_json())
                if len(self._pending_flush) >= max(log_frequency, 1):
                    to_write = self._pending_flush
                    self._pending_flush = []
        self._write(trace_file, to_write)
        on_complete = self.on_complete
        if on_complete is not None:
            try:
                on_complete(trace)
            except Exception:
                pass  # observability must never fail the request path

    def tick_span(self, kind, t0, t1):
        """One continuous-batching scheduler tick as a completed COMPUTE
        span under the synthetic model name ``__lm_<kind>__`` (kinds:
        ``decode``, ``prefill_chunk``).  ``t0``/``t1`` are monotonic
        seconds; the span is stamped onto the wall clock ending now, so
        tick spans interleave with request spans in the exported trace
        file — the per-tick jitter/fairness evidence the LM engine's
        head-of-line and starvation proofs read.

        Ticks subsample on ``trace_rate`` with their OWN counter and land
        in ``tick_completed``: decode ticks fire hundreds of times per
        second, so sharing the request path's ``trace_count`` budget or
        its bounded ``completed`` deque would exhaust the budget (and
        evict every real request trace) within seconds."""
        if not self.enabled():
            return
        rate = max(self._int_setting(self._settings, "trace_rate", 1), 1)
        with self._lock:
            seen = self._tick_seen
            self._tick_seen += 1
            if seen % rate:
                return
            self._seq += 1
            seq = self._seq
        span = RequestTrace(
            gen_trace_id(), gen_span_id(),
            model_name=f"__lm_{kind}__", seq=seq,
        )
        now = time.time_ns()
        span.event("COMPUTE_START", now - int((t1 - t0) * 1e9))
        span.event("COMPUTE_END", now)
        self._complete_into(span, self.tick_completed)

    def _span_seq(self):
        with self._lock:
            self._seq += 1
            return self._seq

    @contextlib.contextmanager
    def peer_span(self, op, peer="", **tags):
        """Bracket one fleet peer RPC with PEER_START/PEER_END.

        A request-thread peer call (prefix/cache/sequence lookup, the
        synchronous durability push) records a CHILD span under the
        thread's active request trace, so a peer fetch shows inside the
        originating request's timeline.  Off-request callers (the
        anti-entropy thread) get a standalone span with its own trace id,
        subsampled on ``trace_rate`` with the tick counter so background
        pushes never drain the request budget.  Yields the span (or None
        when nothing records); callers stamp result tags onto
        ``span.tags`` before the block exits."""
        parent = current_trace()
        if parent is not None:
            span = RequestTrace(
                parent.trace_id, gen_span_id(),
                parent_span_id=parent.span_id,
                model_name=f"__peer_{op}__", protocol="fleet",
                seq=self._span_seq(),
            )
        elif self.enabled():
            rate = max(
                self._int_setting(self._settings, "trace_rate", 1), 1
            )
            with self._lock:
                seen = self._tick_seen
                self._tick_seen += 1
            if seen % rate:
                span = None
            else:
                span = RequestTrace(
                    gen_trace_id(), gen_span_id(),
                    model_name=f"__peer_{op}__", protocol="fleet",
                    seq=self._span_seq(),
                )
        else:
            span = None
        if span is None:
            yield None
            return
        span.tags["op"] = op
        if peer:
            span.tags["peer"] = peer
        span.tags.update(tags)
        span.event("PEER_START")
        try:
            yield span
        except Exception as e:
            span.error = str(e)
            raise
        finally:
            span.event("PEER_END")
            self._complete_into(span, self.peer_completed)

    @contextlib.contextmanager
    def serve_span(self, op, traceparent=None, **tags):
        """The peer-server half of a fleet RPC: a span under the CALLING
        replica's trace id when the frame carried a traceparent — the
        receipt that joins a cross-replica fetch into one trace spanning
        both processes.  Frames with no trace context record nothing
        (the caller decided not to sample)."""
        parent = parse_traceparent(traceparent)
        if parent is None:
            yield None
            return
        span = RequestTrace(
            parent[0], gen_span_id(), parent_span_id=parent[1],
            model_name=f"__peer_{op}__", protocol="fleet",
            seq=self._span_seq(),
        )
        span.tags["op"] = op
        span.tags["side"] = "serve"
        span.tags.update(tags)
        span.event("COMPUTE_START")
        try:
            yield span
        except Exception as e:
            span.error = str(e)
            raise
        finally:
            span.event("COMPUTE_END")
            self._complete_into(span, self.peer_completed)

    def resume_span(self, traceparent, seq_id, **tags):
        """One SEQ_RESUME marker span CONTINUING a replicated snapshot's
        trace id: a survivor resuming a dead replica's durable sequence
        stamps the resume into the ORIGINATING trace, so the failover
        reads as one trace spanning the dead and surviving processes.
        No-op (returns None) when the snapshot carried no trace context."""
        parent = parse_traceparent(traceparent)
        if parent is None:
            return None
        span = RequestTrace(
            parent[0], gen_span_id(), parent_span_id=parent[1],
            model_name="__seq_resume__", protocol="fleet",
            seq=self._span_seq(),
        )
        span.tags["sequence_id"] = seq_id
        span.tags.update(tags)
        span.event("SEQ_RESUME")
        self._complete_into(span, self.peer_completed)
        return span

    def flush(self):
        """Force any buffered records to the trace file (engine close)."""
        trace_file = self._settings.get("trace_file") or ""
        with self._lock:
            to_write = self._pending_flush
            self._pending_flush = []
        self._write(trace_file, to_write)

    @staticmethod
    def _write(trace_file, records):
        if not trace_file or not records:
            return
        try:
            for record in records:
                append_trace_record(trace_file, record)
        except OSError:
            pass  # tracing must never fail the request path
