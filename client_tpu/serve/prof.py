"""Continuous low-overhead phase profiler: where serving time goes.

PR 13's tracing answers *what happened* per request; this module is the
always-on layer that answers *why it was slow*: every engine keeps a
:class:`PhaseProfiler` — a bounded ring of per-tick phase timings
(sibling of ``serve/flight.py``'s FlightRecorder) fed continuously by
the hot paths and rolled up on demand:

- the LM engine's scheduler loop brackets each iteration
  (lock/schedule, prefill/decode dispatch, device wait, token delivery,
  idle wait) with ``perf_counter`` spans,
- the unary engine's execute path folds its existing monotonic
  timestamps (input gather / model fn / render) into ``unary`` ticks at
  zero added timing cost,
- the HTTP/gRPC frontends and the perf client backends commit
  wire-path ticks (deserialize / execute-wait / serialize / send).

Rollups attribute windowed wall time into per-phase shares, and the
measured device time + per-model FLOP figures produce compute-share and
MFU series (``ctpu_prof_*`` gauges/counters in serve/metrics.py's
catalog).  :func:`device_peak_tflops` supplies the MFU denominator —
the advertised TPU bf16 peak, or a measured host GEMM peak off-TPU
(``cpu_fallback``) so attribution ratios are non-null everywhere.

Surfaces: ``GET /v2/debug/prof`` (rollup JSON),
``python -m client_tpu.profview`` (attribution tables), flight-recorder
dumps (the last N tick profiles ride along), and bench.py's ``prof``
block.

Bracket discipline: a handle acquired with ``start_tick`` MUST reach
``finish`` on every exit path (``with`` handle, or ``try/finally``) —
the SPAN-LEAK lint rule enforces this shape (analysis/resources.py
registers ``start_tick`` in the span vocabulary).  An unfinished tick
never reaches the ring, so the rollup under-attributes exactly when a
failure makes the timeline interesting.

Everything here must stay cheap enough to leave armed in production:
one perf_counter pair per phase, one deque append per tick, no
allocation beyond the record dict.  The measured budget (bench
``prof_overhead_pct``, tests/test_prof.py) is <= 2% on the in-process
headline path.
"""

import collections
import threading
import time

from client_tpu.analysis.witness import witness_shared

__all__ = [
    "PhaseProfiler",
    "NULL_TICK",
    "ATTRIBUTION_GROUPS",
    "device_peak_tflops",
    "host_peak_tflops",
    "attribute_phases",
]

# Advertised dense bf16 peaks by TPU device kind (the MFU denominator;
# bench.py delegates here so the table has one home).
_TPU_PEAKS = (
    ("v5 lite", 197.0), ("v5e", 197.0),
    ("v5p", 459.0), ("v5", 459.0),
    ("v6", 918.0),                      # Trillium
    ("v4", 275.0), ("v3", 123.0),
)

# Phase -> attribution bucket for the dispatch/compute/host/idle split
# (bench's prof block, profview's summary row).  On the CPU test
# platform jitted "dispatch" blocks until the computation finishes, so
# the dispatch-site phases are device work, not launch overhead — they
# group under compute; the device_wait phase (readback/np.asarray) is
# where async TPU dispatch actually pays.
ATTRIBUTION_GROUPS = {
    "compute": ("compute", "decode_dispatch", "prefill_dispatch",
                "verify_dispatch", "device_wait"),
    "dispatch": ("schedule", "preempt", "resume", "execute"),
    "host": ("host", "render", "deliver", "sample", "serialize",
             "deserialize", "send", "wait", "draft"),
    "idle": ("idle",),
}

_peak_cache = None
_peak_lock = threading.Lock()


def host_peak_tflops(n=384, reps=3):
    """Measured host GEMM peak in TFLOP/s (best of *reps* numpy matmuls
    of an n x n fp32 problem) — the off-TPU MFU denominator.  A probe,
    not an advertised figure: BLAS-backed numpy lands within a small
    factor of the host's real dense peak, which is all an attribution
    *ratio* needs."""
    import numpy as np

    a = np.ones((n, n), np.float32)
    b = np.ones((n, n), np.float32)
    a @ b  # warm the BLAS path outside the timed reps
    best = float("inf")
    for _ in range(max(1, int(reps))):
        t0 = time.perf_counter()
        a @ b
        best = min(best, time.perf_counter() - t0)
    flops = 2.0 * n * n * n
    return max(flops / max(best, 1e-9) / 1e12, 1e-6)


def device_peak_tflops():
    """(peak_tflops, peak_kind) of the local accelerator.

    TPU kinds map to their advertised dense bf16 peaks; anything else
    (the CPU test platform, an unrecognized device) falls back to the
    measured host GEMM peak tagged ``"cpu_fallback"`` so MFU figures
    are non-null everywhere.  Cached: the probe runs once per process.
    """
    global _peak_cache
    with _peak_lock:
        if _peak_cache is not None:
            return _peak_cache
        kind = ""
        try:
            import jax

            kind = getattr(jax.devices()[0], "device_kind", "").lower()
        except Exception:
            pass
        for pat, peak in _TPU_PEAKS:
            if pat in kind:
                _peak_cache = (peak, "tpu")
                return _peak_cache
        _peak_cache = (round(host_peak_tflops(), 4), "cpu_fallback")
        return _peak_cache


def attribute_phases(phases, wall_s=None):
    """Fold a {phase: seconds} dict into the dispatch/compute/host/idle
    share split (percentages summing to ~100).

    *wall_s* is the window the phases were measured over; time it
    covers beyond the summed phases counts as idle.  Concurrent
    execution can sum past the wall — shares then normalize over the
    summed total (idle 0)."""
    groups = {"compute": 0.0, "dispatch": 0.0, "host": 0.0, "idle": 0.0}
    for name, seconds in (phases or {}).items():
        for group, members in ATTRIBUTION_GROUPS.items():
            if name in members:
                groups[group] += seconds
                break
        else:
            groups["host"] += seconds  # unmapped phases are host work
    covered = sum(groups.values())
    if wall_s is not None and wall_s > covered:
        groups["idle"] += wall_s - covered
    total = sum(groups.values())
    if total <= 0.0:
        return None
    return {
        f"{group}_pct": round(100.0 * seconds / total, 2)
        for group, seconds in groups.items()
    }


class _Phase:
    """One ``with tick.phase(name):`` bracket — accumulates elapsed
    seconds into the owning tick's phase dict on exit."""

    __slots__ = ("_tick", "_name", "_t0")

    def __init__(self, tick, name):
        self._tick = tick
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tick.add(self._name, time.perf_counter() - self._t0)
        return False


class _NullPhase:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_PHASE = _NullPhase()


class _Tick:
    """One in-progress tick: phase durations + attribution meta,
    committed to the profiler's ring by ``finish`` (or ``close`` /
    ``with``)."""

    __slots__ = ("prof", "kind", "t0", "phases", "meta", "_items",
                 "_flops", "_model")

    def __init__(self, prof, kind):
        self.prof = prof
        self.kind = kind
        self.phases = {}
        self.meta = None
        self._items = 0
        self._flops = 0.0
        self._model = None
        self.t0 = time.perf_counter()

    def phase(self, name):
        return _Phase(self, name)

    def relabel(self, kind):
        """Retag the tick once the iteration knows what it did (a
        scheduler tick starts as "sched" and becomes decode/prefill/
        idle)."""
        self.kind = kind

    def add(self, name, seconds):
        """Fold a pre-measured duration into phase *name* (the unary
        path reuses its existing monotonic timestamps this way)."""
        self.phases[name] = self.phases.get(name, 0.0) + seconds

    def compute(self, model, items, flops_per_item=None):
        """Count device work delivered this tick (MFU numerator): the
        device seconds come from the tick's own compute-group phases."""
        self._model = model
        self._items += int(items)
        if flops_per_item:
            self._flops += float(flops_per_item) * int(items)

    def note(self, **meta):
        if self.meta is None:
            self.meta = {}
        self.meta.update(meta)

    def close(self):
        self.prof.finish(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.prof.finish(self)
        return False


class _NullTick:
    """Disarmed profiler's handle: every bracket is a no-op."""

    __slots__ = ()
    kind = None

    def phase(self, name):
        return _NULL_PHASE

    def relabel(self, kind):
        pass

    def add(self, name, seconds):
        pass

    def compute(self, model, items, flops_per_item=None):
        pass

    def note(self, **meta):
        pass

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_TICK = _NullTick()

# compute-group phase names (device seconds of one tick) — derived once
_DEVICE_PHASES = frozenset(ATTRIBUTION_GROUPS["compute"])


@witness_shared("_lock")
class PhaseProfiler:
    """Bounded ring of per-tick phase timings with windowed rollups.

    Always-on and cheap: ``start_tick``/``finish`` bracket one scheduler
    iteration / request / RPC; ``commit`` folds pre-measured durations
    in one call (the unary hot path).  Consecutive ``idle`` ticks
    coalesce in place so a quiet engine doesn't churn the ring.

    ``registry`` (late-bindable) receives the ``ctpu_prof_*`` series;
    per-model FLOP counts committed via ``_Tick.compute`` update the
    MFU and compute-share gauges using :func:`device_peak_tflops`.
    """

    def __init__(self, name="", capacity=4096, registry=None,
                 window_s=60.0, flush_interval_s=0.25):
        self.name = str(name)
        self.capacity = int(capacity)
        self.window_s = float(window_s)
        self.flush_interval_s = float(flush_interval_s)
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=self.capacity)
        self._totals = {}        # phase -> cumulative seconds
        self._kinds = {}         # tick kind -> count
        self._wall_s = 0.0       # cumulative tick wall seconds
        self._models = {}        # model -> [device_s, items, flops]
        self._children = []      # adopted engine profilers (LM scheds)
        self._armed = True
        self.registry = registry
        self.ticks_noted = 0
        # metric deltas batched between registry flushes: exporting on
        # every commit costs several label-formatted registry ops per
        # tick, which alone would blow the <=2% overhead budget on a
        # cheap unary path.
        self._pending_ticks = {}   # kind -> count since last flush
        self._pending_phases = {}  # phase -> seconds since last flush
        self._last_flush = 0.0

    # -- arming ------------------------------------------------------------

    @property
    def armed(self):
        return self._armed

    def arm(self, on=True):
        """Toggle recording (the overhead-measurement hook; the profiler
        is armed by default).  Disarmed, ``start_tick`` hands out the
        shared no-op tick and ``commit`` returns immediately."""
        with self._lock:
            self._armed = bool(on)

    def set_registry(self, registry):
        with self._lock:
            self.registry = registry

    def adopt(self, child):
        """Register a per-engine child profiler (the LM scheduler's) so
        reports and flight dumps cover every engine in the server."""
        if child is None or child is self:
            return
        with self._lock:
            if child not in self._children:
                self._children.append(child)

    # -- recording ---------------------------------------------------------

    def start_tick(self, kind):
        """A new tick handle (or the no-op handle when disarmed).  The
        caller MUST finish it on every exit path: ``with`` the handle,
        or ``finish``/``close`` inside a ``finally`` — the SPAN-LEAK
        lint shape."""
        if not self._armed:
            return NULL_TICK
        return _Tick(self, kind)

    def finish(self, tick, kind=None):
        """Commit one tick handle to the ring (idempotent for the no-op
        handle)."""
        if tick is NULL_TICK or tick is None:
            return
        t1 = time.perf_counter()
        self.commit(
            kind if kind is not None else tick.kind,
            t1 - tick.t0,
            phases=tick.phases,
            model=tick._model,
            items=tick._items,
            flops=tick._flops,
            meta=tick.meta,
        )

    def commit(self, kind, dur_s, phases=None, model=None, items=0,
               flops=0.0, flops_per_item=None, meta=None):
        """Fold one pre-measured tick into the ring and rollup state —
        the zero-extra-clock path the unary engine and frontends use.
        ``flops_per_item`` is a convenience for callers that count items
        but carry per-item FLOP figures."""
        if not self._armed:
            return
        phases = phases or {}
        if flops_per_item and items:
            flops = float(flops) + float(flops_per_item) * int(items)
        device_s = 0.0
        for name, seconds in phases.items():
            if name in _DEVICE_PHASES:
                device_s += seconds
        record = {
            "ts": time.time(),
            "kind": str(kind),
            "dur_s": dur_s,
            "phases": phases,
        }
        if model is not None:
            record["model"] = str(model)
        if items:
            record["items"] = int(items)
        if meta:
            record.update(meta)
        flush = None
        with self._lock:
            ring = self._ring
            if (kind == "idle" and ring
                    and ring[-1]["kind"] == "idle"):
                # coalesce idle runs: a quiet engine must not wash real
                # ticks out of the bounded ring
                last = ring[-1]
                last["dur_s"] += dur_s
                last["ticks"] = last.get("ticks", 1) + 1
                for name, seconds in phases.items():
                    last["phases"][name] = (
                        last["phases"].get(name, 0.0) + seconds
                    )
            else:
                ring.append(record)
            self.ticks_noted += 1
            self._wall_s += dur_s
            self._kinds[kind] = self._kinds.get(kind, 0) + 1
            totals = self._totals
            pending = self._pending_phases
            for name, seconds in phases.items():
                totals[name] = totals.get(name, 0.0) + seconds
                pending[name] = pending.get(name, 0.0) + seconds
            self._pending_ticks[kind] = (
                self._pending_ticks.get(kind, 0) + 1
            )
            if model is not None and (device_s or items):
                entry = self._models.setdefault(model, [0.0, 0, 0.0])
                entry[0] += device_s
                entry[1] += int(items)
                entry[2] += float(flops)
            if (self.registry is not None
                    and record["ts"] - self._last_flush
                    >= self.flush_interval_s):
                flush = self._drain_pending_locked(record["ts"])
        if flush is not None:
            self._export(*flush)

    def _drain_pending_locked(self, now):
        """Grab-and-reset the batched metric deltas (caller holds the
        ring lock); returns the _export argument tuple."""
        ticks, self._pending_ticks = self._pending_ticks, {}
        phases, self._pending_phases = self._pending_phases, {}
        models = {m: list(v) for m, v in self._models.items()}
        self._last_flush = now
        return self.registry, ticks, phases, models

    def flush_metrics(self):
        """Force the batched ctpu_prof_* deltas out to the registry now
        (reports and tests; the commit path flushes on its own interval)."""
        with self._lock:
            if self.registry is None:
                return
            flush = self._drain_pending_locked(time.time())
        self._export(*flush)

    def _export(self, registry, ticks, phases, models):
        """Push one batch of metric deltas to the registry (outside the
        ring lock; the registry has its own)."""
        from client_tpu.serve.metrics import PROF_HELP

        engine = self.name
        for kind, count in ticks.items():
            registry.inc(
                "ctpu_prof_ticks_total", {"engine": engine, "kind": kind},
                value=count,
                help_=PROF_HELP["ctpu_prof_ticks_total"],
            )
        for name, seconds in phases.items():
            registry.inc(
                "ctpu_prof_phase_seconds_total",
                {"engine": engine, "phase": name}, value=seconds,
                help_=PROF_HELP["ctpu_prof_phase_seconds_total"],
            )
        total_device = sum(v[0] for v in models.values())
        for model, (dev, _items, total_flops) in models.items():
            if total_device > 0.0:
                registry.set(
                    "ctpu_prof_compute_share_pct",
                    {"engine": engine, "model": model},
                    round(100.0 * dev / total_device, 3),
                    help_=PROF_HELP["ctpu_prof_compute_share_pct"],
                )
            if total_flops and dev > 0.0:
                peak, _kind = device_peak_tflops()
                registry.set(
                    "ctpu_prof_mfu_pct",
                    {"engine": engine, "model": model},
                    round(100.0 * total_flops / (dev * peak * 1e12), 4),
                    help_=PROF_HELP["ctpu_prof_mfu_pct"],
                )

    # -- reading -----------------------------------------------------------

    def snapshot(self, last=None):
        """The ring's records, oldest first (the last *last* when set)."""
        with self._lock:
            records = list(self._ring)
        if last is not None:
            records = records[-int(last):]
        return records

    def recent(self, last=16):
        """The last *last* tick records of this profiler AND every
        adopted child, each tagged with its engine name — what flight
        dumps carry."""
        with self._lock:
            children = list(self._children)
        out = []
        for prof in [self] + children:
            for record in prof.snapshot(last=last):
                tagged = dict(record)
                tagged["engine"] = prof.name
                out.append(tagged)
        out.sort(key=lambda r: r.get("ts", 0.0))
        return out

    def rollup(self, window_s=None, kinds=None):
        """Windowed attribution summary of this profiler's ring.

        *window_s* bounds the records considered (None = the profiler's
        default window; 0/negative = everything in the ring); *kinds*
        optionally filters tick kinds.  Returns phase totals with
        percentages, tick counts by kind, per-model device share / MFU,
        and the dispatch/compute/host/idle split."""
        if window_s is None:
            window_s = self.window_s
        cutoff = time.time() - window_s if window_s > 0 else None
        records = self.snapshot()
        if cutoff is not None:
            records = [r for r in records if r["ts"] >= cutoff]
        if kinds is not None:
            allowed = set(kinds)
            records = [r for r in records if r["kind"] in allowed]
        phases = {}
        kind_counts = {}
        models = {}
        wall = 0.0
        ticks = 0
        for record in records:
            n = record.get("ticks", 1)
            ticks += n
            wall += record["dur_s"]
            kind_counts[record["kind"]] = (
                kind_counts.get(record["kind"], 0) + n
            )
            device_s = 0.0
            for name, seconds in record["phases"].items():
                phases[name] = phases.get(name, 0.0) + seconds
                if name in _DEVICE_PHASES:
                    device_s += seconds
            model = record.get("model")
            if model is not None:
                entry = models.setdefault(model, [0.0, 0])
                entry[0] += device_s
                entry[1] += record.get("items", 0)
        covered = sum(phases.values())
        phase_rows = {
            name: {
                "s": round(seconds, 6),
                "pct": round(100.0 * seconds / covered, 2) if covered
                else 0.0,
            }
            for name, seconds in sorted(
                phases.items(), key=lambda kv: -kv[1]
            )
        }
        peak, peak_kind = device_peak_tflops()
        total_device = sum(v[0] for v in models.values())
        with self._lock:
            flops_by_model = {
                m: v[2] for m, v in self._models.items()
            }
        model_rows = {}
        for model, (device_s, items) in sorted(models.items()):
            row = {
                "device_s": round(device_s, 6),
                "items": items,
                "compute_share_pct": (
                    round(100.0 * device_s / total_device, 2)
                    if total_device else 0.0
                ),
            }
            flops = flops_by_model.get(model)
            if flops and device_s > 0.0:
                # lifetime FLOP/s over lifetime device time: the ring
                # window carries items but not flops per record
                with self._lock:
                    life = self._models.get(model)
                if life and life[0] > 0.0:
                    row["mfu_pct"] = round(
                        100.0 * life[2] / (life[0] * peak * 1e12), 4
                    )
            model_rows[model] = row
        return {
            "engine": self.name,
            "window_s": window_s,
            "ticks": ticks,
            "wall_s": round(wall, 6),
            "covered_s": round(covered, 6),
            "kinds": kind_counts,
            "phases": phase_rows,
            "models": model_rows,
            "attribution": attribute_phases(phases, wall_s=wall),
            "peak_tflops": peak,
            "peak_kind": peak_kind,
        }

    def report(self, window_s=None):
        """This profiler's rollup plus every adopted child's — the
        ``/v2/debug/prof`` payload and profview's input."""
        with self._lock:
            children = list(self._children)
        for prof in [self] + children:
            prof.flush_metrics()
        return {
            "kind": "prof_report",
            "ts": time.time(),
            "engines": [
                prof.rollup(window_s=window_s)
                for prof in [self] + children
            ],
        }
