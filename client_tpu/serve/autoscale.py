"""Elastic fleet autoscaler: the control loop that closes PR 12's
sensing/actuation gap.

The fleet already *senses* load (``EndpointPool.pressures()`` — per-
replica queue depth, paged-KV occupancy and prefix-affinity pressure
gossiped on health probes) and already *actuates* safely (``drain()``
migrates live sequences, parked LM streams and hot cache/prefix content
to surviving peers; the anti-entropy push + probation ramp warm a new
replica before it takes full traffic).  This module is the loop in the
middle:

- **scale-up** when queue depth or KV occupancy crosses the policy's
  high watermark for ``up_after`` consecutive ticks: a new replica is
  spawned, joined to the peer mesh with the hottest survivor FIRST in
  its peer order (prefix-aware placement — its misses land on the
  replica most likely to hold the chains), warmed by one anti-entropy
  round from that survivor, and only then offered to the pool — where
  the probation + ramp-up machinery (not this module) gates its traffic
  share.
- **scale-down** when the whole fleet sits below the low watermark for
  ``down_after`` ticks: the lowest-pressure replica is RETIRED from the
  pool (immediately unroutable, in-flight finishes) and then drained —
  never killed — so nothing a client could notice is lost.
- **hysteresis + cooldown** keep a bursty diurnal ramp from flapping:
  watermark crossings must persist across ticks, and any action starts
  a cooldown window during which further decisions are suppressed (and
  counted: ``ctpu_autoscale_flap_suppressed_total``).

The loop never touches an engine or pool lock across a peer call: every
spawn/retire/warm runs on the autoscaler's own thread with only its own
bookkeeping lock held around list mutation.
"""

import threading
import time

from client_tpu.serve.metrics import AUTOSCALE_HELP

__all__ = [
    "AutoscalePolicy",
    "Autoscaler",
    "ReplicaHandle",
    "ServerReplicaLauncher",
]


class AutoscalePolicy:
    """Watermarks, hysteresis and pacing for the control loop.

    ``scale_up_at`` / ``scale_down_at`` are per-replica queue-depth
    watermarks (the gossiped ``queue_depth`` pressure signal);
    ``kv_scale_up_at`` is the paged-KV occupancy fraction that forces a
    scale-up regardless of queue depth (block exhaustion is the
    earliest LM scale signal — admission backpressure hits before the
    queue looks deep).  ``up_after``/``down_after`` are consecutive-tick
    hysteresis floors, ``cooldown_s`` the post-action suppression
    window.
    """

    def __init__(self, min_replicas=1, max_replicas=4, scale_up_at=8.0,
                 scale_down_at=1.0, kv_scale_up_at=0.85, up_after=2,
                 down_after=3, cooldown_s=10.0, tick_interval_s=1.0):
        self.min_replicas = max(int(min_replicas), 1)
        self.max_replicas = max(int(max_replicas), self.min_replicas)
        self.scale_up_at = float(scale_up_at)
        self.scale_down_at = float(scale_down_at)
        if self.scale_down_at >= self.scale_up_at:
            raise ValueError(
                "scale_down_at must sit strictly below scale_up_at "
                f"({self.scale_down_at} >= {self.scale_up_at}) — equal "
                "watermarks oscillate on every tick"
            )
        self.kv_scale_up_at = float(kv_scale_up_at)
        self.up_after = max(int(up_after), 1)
        self.down_after = max(int(down_after), 1)
        self.cooldown_s = float(cooldown_s)
        self.tick_interval_s = float(tick_interval_s)


class ReplicaHandle:
    """One managed replica: the routable url plus (optionally) the
    in-process objects a launcher wants retire() to reach.  ``tier``
    (a :class:`~client_tpu.serve.fleet.FleetTier`) enables peer-mesh
    wiring and anti-entropy warming; launchers managing out-of-process
    replicas may leave it None and do their own wiring."""

    def __init__(self, url, fleet_address=None, tier=None, server=None,
                 proxy=None):
        self.url = str(url)
        self.fleet_address = fleet_address
        self.tier = tier
        self.server = server
        self.proxy = proxy

    def __repr__(self):
        return f"ReplicaHandle({self.url!r}, fleet={self.fleet_address!r})"


class ServerReplicaLauncher:
    """Default launcher: in-process :class:`~client_tpu.serve.Server`
    replicas, each with an attached started
    :class:`~client_tpu.serve.fleet.FleetTier`.

    ``models_factory()`` builds a fresh model list per replica (model
    objects hold per-replica state and must not be shared).  ``retire``
    is the planned-exit path: the server drains (sequences, parked
    streams and hot content migrate through its still-wired tier), then
    the tier closes.
    """

    def __init__(self, models_factory, fleet_kwargs=None,
                 server_kwargs=None, drain_timeout_s=30.0):
        self.models_factory = models_factory
        self.fleet_kwargs = dict(fleet_kwargs or {})
        self.server_kwargs = dict(server_kwargs or {})
        self.drain_timeout_s = float(drain_timeout_s)

    def spawn(self):
        from client_tpu.serve import Server
        from client_tpu.serve.fleet import FleetTier

        tier = FleetTier(**self.fleet_kwargs).start()
        server = Server(
            models=self.models_factory(), with_default_models=False,
            fleet=tier, **self.server_kwargs,
        ).start()
        return ReplicaHandle(
            server.http_address, fleet_address=tier.address,
            tier=tier, server=server,
        )

    def retire(self, handle):
        # drain BEFORE closing the tier: the drain-time exports travel
        # through it to the surviving peers.  Flush the anti-entropy
        # queue synchronously after the drain — exports still queued
        # when the tier closes would die with it.
        if handle.server is not None:
            handle.server.drain(self.drain_timeout_s)
        if handle.tier is not None:
            try:
                handle.tier.replicate_now()
            except Exception:  # noqa: BLE001 - retire must finish
                pass
            handle.tier.close()


class Autoscaler:
    """The control loop.  Drive it synchronously (``tick()`` — tests and
    the bench own the clock) or via ``start()``/``close()`` (a daemon
    thread ticking every ``policy.tick_interval_s``) — one driver at a
    time, never both: ticks are single-threaded by contract, so no lock
    is ever held across the spawn/retire/warm peer traffic a tick
    issues (the internal lock guards only the replica list and
    counters, for concurrent ``status()``/``replicas()`` readers)."""

    def __init__(self, pool, launcher, policy=None, registry=None):
        self.pool = pool
        self.launcher = launcher
        self.policy = policy if policy is not None else AutoscalePolicy()
        self.registry = registry
        self._lock = threading.Lock()        # replica list + counters
        self._replicas = []
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_at = None
        self.scale_ups = 0
        self.scale_downs = 0
        self.flap_suppressed = 0
        self._stop = threading.Event()
        self._thread = None

    # -- membership --------------------------------------------------------

    def adopt(self, handles):
        """Seed the managed set with already-running replicas (the
        fixture/CLI spawns the floor itself, the autoscaler steers from
        there).  Wires the peer mesh and publishes the membership to
        the pool."""
        with self._lock:
            self._replicas.extend(handles)
        self._wire_peers()
        self._publish_membership()
        self._gauge()
        return self

    def replicas(self):
        with self._lock:
            return list(self._replicas)

    # -- control loop ------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        with self._lock:
            self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="autoscaler", daemon=True,
        )
        self._thread.start()
        return self

    def close(self):
        """Stop the loop thread.  Managed replicas stay up — shutdown
        ownership belongs to whoever spawned the floor."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.policy.tick_interval_s):
            try:
                self.tick()
            except Exception:  # pragma: no cover - defensive
                pass

    def tick(self, now=None):
        """One control decision.  Returns the action taken: ``"up"``,
        ``"down"``, ``"suppressed"`` (cooldown ate a triggered action)
        or None (steady state / hysteresis still filling)."""
        return self._tick(time.monotonic() if now is None else now)

    def _tick(self, now):
        policy = self.policy
        queue_max, kv_max, fresh = self._signals()
        over = fresh and (
            queue_max >= policy.scale_up_at
            or kv_max >= policy.kv_scale_up_at
        )
        under = fresh and (
            queue_max <= policy.scale_down_at
            and kv_max < policy.kv_scale_up_at
        )
        # decide under the lock (streaks/cooldown are status()-visible
        # state); act — spawn/retire peer traffic — strictly outside it
        with self._lock:
            n = len(self._replicas)
            self._up_streak = self._up_streak + 1 if over else 0
            self._down_streak = self._down_streak + 1 if under else 0
            want_up = (
                self._up_streak >= policy.up_after
                and n < policy.max_replicas
            )
            want_down = (
                self._down_streak >= policy.down_after
                and n > policy.min_replicas
            )
            if not want_up and not want_down:
                return None
            if (
                self._last_action_at is not None
                and now - self._last_action_at < policy.cooldown_s
            ):
                self.flap_suppressed += 1
                suppressed = True
            else:
                suppressed = False
                self._last_action_at = now
                if want_up:
                    self._up_streak = 0
                else:
                    self._down_streak = 0
        if suppressed:
            self._count("ctpu_autoscale_flap_suppressed_total")
            return "suppressed"
        if want_up:
            self._scale_up()
            return "up"
        self._scale_down(queue_key="queue_depth")
        return "down"

    def _signals(self):
        """(max queue depth, max KV fraction, any-fresh-signal) over the
        pool's freshness-filtered pressure view.  Stale/never-gossiped
        replicas read as no signal — a dead replica cannot steer the
        loop (see EndpointPool.pressures)."""
        queue_max, kv_max, fresh = 0.0, 0.0, False
        for pressure in self.pool.pressures().values():
            if not pressure:
                continue
            fresh = True
            queue_max = max(queue_max, float(pressure.get("queue_depth", 0)))
            kv_max = max(
                kv_max, float(pressure.get("kv_used_fraction", 0.0))
            )
        return queue_max, kv_max, fresh

    # -- actions -----------------------------------------------------------

    def _scale_up(self):
        handle = self.launcher.spawn()
        warm = self._warmest()
        with self._lock:
            self._replicas.append(handle)
            self.scale_ups += 1
        self._wire_peers(prefer=warm)
        # one anti-entropy round from the hottest survivor warms the new
        # replica's prefix/cache stores BEFORE the pool offers it
        # traffic (probation + ramp-up then pace the offered share)
        if warm is not None and warm.tier is not None:
            try:
                warm.tier.replicate_now()
            except Exception:  # noqa: BLE001 - warming is best-effort
                pass
        self._publish_membership()
        self._count("ctpu_autoscale_scale_ups_total")
        self._gauge()

    def _scale_down(self, queue_key="queue_depth"):
        pressures = self.pool.pressures()
        with self._lock:
            if len(self._replicas) <= self.policy.min_replicas:
                return
            # victim: lowest queued work; ties break toward the newest
            # replica (LIFO — the longest-lived replicas hold the most
            # affinity state)
            victim = min(
                reversed(self._replicas),
                key=lambda h: float(
                    (pressures.get(h.url) or {}).get(queue_key, 0)
                ),
            )
            self._replicas.remove(victim)
            self.scale_downs += 1
        # retire order matters: (1) the pool stops routing to the victim
        # (RETIRING: in-flight finishes, nothing new arrives), (2) the
        # victim — whose OWN peer list still names every survivor —
        # drains, migrating live sequences, parked streams and hot
        # content outward, (3) only THEN do survivors drop it from
        # their peer mesh.  Rewiring before the drain would sever the
        # live-pull path: a sticky sequence re-routed off the victim
        # mid-drain resumes via a survivor's peer lookup, which must
        # still be able to ask the victim for its live (never yet
        # pushed) sequence state.
        self._publish_membership()
        self.launcher.retire(victim)
        self._wire_peers()
        self._count("ctpu_autoscale_scale_downs_total")
        self._gauge()

    def _warmest(self):
        """The managed replica with the most prefix-affinity pressure —
        the anti-entropy warm source for a newcomer, and the head of its
        peer order (prefix-aware placement)."""
        pressures = self.pool.pressures()
        best, best_hot = None, -1.0
        for handle in self.replicas():
            hot = float(
                (pressures.get(handle.url) or {}).get("prefix_hot", 0)
            )
            if hot > best_hot:
                best, best_hot = handle, hot
        return best

    def _wire_peers(self, prefer=None):
        """Point every managed tier at every other replica's fleet
        address.  *prefer* (a handle) is placed FIRST in the others'
        peer lists — bounded-fan-out lookups try it before anyone else,
        which is what makes placement prefix-aware."""
        handles = self.replicas()
        addresses = {
            id(h): h.fleet_address
            for h in handles if h.fleet_address is not None
        }
        for handle in handles:
            if handle.tier is None:
                continue
            peers = [
                addr for hid, addr in addresses.items()
                if hid != id(handle)
            ]
            if prefer is not None and prefer is not handle:
                paddr = prefer.fleet_address
                if paddr in peers:
                    peers.remove(paddr)
                    peers.insert(0, paddr)
            handle.tier.set_peers(peers)

    def _publish_membership(self):
        urls = [h.url for h in self.replicas()]
        if urls:
            self.pool.update_endpoints(urls)

    # -- metrics / introspection -------------------------------------------

    def _count(self, name, value=1):
        if self.registry is not None:
            self.registry.inc(name, None, value=value,
                              help_=AUTOSCALE_HELP[name])

    def _gauge(self):
        if self.registry is not None:
            with self._lock:
                n = len(self._replicas)
            self.registry.set(
                "ctpu_autoscale_replicas", None, n,
                help_=AUTOSCALE_HELP["ctpu_autoscale_replicas"],
            )

    def status(self):
        with self._lock:
            return {
                "replicas": len(self._replicas),
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "flap_suppressed": self.flap_suppressed,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
            }
