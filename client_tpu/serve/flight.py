"""Flight recorder: a bounded in-memory ring of recent observability
events, dumped as JSON-lines on demand and automatically on anomaly.

Postmortems must not depend on having had tracing enabled or a scraper
attached when the anomaly happened.  Each server keeps one
:class:`FlightRecorder` (``engine.flight``) fed continuously and cheaply:

- every completed trace span (request timelines, LM tick spans, fleet
  peer spans) via the tracer's ``on_complete`` hook,
- discrete events the subsystems note directly — preemptions and engine
  wedges (serve/lm/engine.py), SLO breaches (serve/slo.py), chaos
  invariant failures (testing/chaos.py), breaker/peer errors.

The ring is bounded (default 4096 records) so a server that runs for
weeks holds the *recent* past, which is what a postmortem needs.  A dump
writes the whole ring as JSON-lines prefixed with a header record naming
the reason; triggers are the debug endpoint (``GET /v2/debug/flight``),
an SLO breach, an LM engine wedge, and a chaos invariant failure.  Dumps
land under ``dump_dir`` (constructor arg, else ``$TPU_FLIGHT_DIR``, else
the system temp dir) — ``make chaos`` / ``make soak`` point
``TPU_FLIGHT_DIR`` at ``build/flight/`` so failures archive their dumps.

Everything here is best-effort by design: a full disk or unwritable
directory must never fail the request path, so :meth:`dump` returns None
on failure instead of raising.
"""

import collections
import json
import os
import tempfile
import threading
import time

__all__ = ["FlightRecorder"]


class FlightRecorder:
    """Bounded ring of observability events with JSON-lines dumps."""

    def __init__(self, capacity=4096, dump_dir=None, registry=None,
                 name="", prof=None):
        self.capacity = int(capacity)
        self.dump_dir = dump_dir
        self.registry = registry
        self.name = str(name)  # distinguishes replicas sharing a dir
        self.prof = prof  # PhaseProfiler whose ticks ride along in dumps
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=self.capacity)
        self._dump_seq = 0
        self.events_noted = 0
        self.dumps = []  # paths of every dump written, in order

    # -- feeding -----------------------------------------------------------

    def note(self, kind, **fields):
        """Append one event record (cheap: one deque append under the
        lock; dropped fields must already be JSON-safe)."""
        record = {"kind": str(kind), "ts": time.time()}
        record.update(fields)
        with self._lock:
            self._ring.append(record)
            self.events_noted += 1

    def note_span(self, span):
        """Tracer completion hook: fold a finished trace span into the
        ring (``Tracer.on_complete`` / ``ClientTracer`` compatible —
        anything with ``to_json()``)."""
        try:
            self.note("span", span=span.to_json())
        except Exception:
            pass  # a hostile span must not break recording

    # -- reading / dumping -------------------------------------------------

    def snapshot(self):
        """The ring's current records, oldest first."""
        with self._lock:
            return list(self._ring)

    def render(self, reason=""):
        """The dump payload as a JSON-lines string (the debug endpoint
        serves this without touching the filesystem)."""
        records = self.snapshot()
        header = {
            "kind": "flight_dump",
            "ts": time.time(),
            "reason": str(reason),
            "name": self.name,
            "events": len(records),
        }
        lines = [json.dumps(header, separators=(",", ":"))]
        lines.extend(
            json.dumps(r, separators=(",", ":"), default=str)
            for r in records
        )
        if self.prof is not None:
            # the last N tick profiles ride along so a postmortem sees
            # where time was going right before the anomaly
            try:
                for record in self.prof.recent(last=32):
                    tagged = dict(record)
                    tagged["tick_kind"] = tagged.pop("kind", None)
                    tagged["kind"] = "prof_tick"
                    lines.append(
                        json.dumps(tagged, separators=(",", ":"),
                                   default=str)
                    )
            except Exception:
                pass  # profiling must never break a dump
        return "\n".join(lines) + "\n"

    def _dir(self):
        return (
            self.dump_dir
            or os.environ.get("TPU_FLIGHT_DIR")
            or os.path.join(tempfile.gettempdir(), "ctpu-flight")
        )

    def dump(self, reason):
        """Write the ring as one JSON-lines file under the dump dir and
        return its path — or None when the write failed (a postmortem
        aid must never fail the path that is already failing)."""
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
        directory = self._dir()
        tag = f"-{self.name}" if self.name else ""
        path = os.path.join(
            directory,
            f"flight{tag}-{os.getpid()}-{seq:03d}-{_slug(reason)}.jsonl",
        )
        try:
            os.makedirs(directory, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                f.write(self.render(reason))
        except OSError:
            return None
        with self._lock:
            self.dumps.append(path)
        if self.registry is not None:
            from client_tpu.serve.metrics import SLO_HELP

            self.registry.inc(
                "ctpu_flight_dumps_total", {"reason": _slug(reason)},
                help_=SLO_HELP["ctpu_flight_dumps_total"],
            )
        return path


def _slug(reason):
    """Filesystem-safe reason tag."""
    out = "".join(
        c if c.isalnum() or c in "-_" else "-" for c in str(reason)
    )
    return out[:48] or "manual"
