"""Continuous-batching LM engine: bucketed chunked prefill over a paged
KV pool, prefix-cache block sharing, priority preemption with host-side
swap, lane autoscaling, per-lane sampling, tenant-aware admission.

Scheduling model (one scheduler thread, every device dispatch outside
the condition lock — the LOCK-DISPATCH/BLOCK-UNDER-LOCK invariant the
lint gate enforces):

Each scheduler pass runs AT MOST one prefill chunk and then one decode
tick.  That 1:1 interleave is the head-of-line fix: a novel max-length
prompt used to run its whole prefill (and, for a novel length, a full
XLA compile) between decode ticks, stalling every active token stream;
now the stall per pass is bounded by one fixed-width chunk whose shape
comes from a small geometric bucket set (``policy.chunk_plan``), so the
compile set is bounded too.

Static shapes everywhere (TPU-first):

- decode ticks run at one of a few precompiled lane counts
  (``lane_counts``), stepped by :class:`policy.LaneAutoscaler` on
  sustained queue depth — one executable per count, ever;
- the KV cache is a paged block pool (:class:`kv.KvBlockPool`): per-lane
  block tables gather the logical cache inside the jitted programs, and
  the new token's K/V scatters to ``(table[pos // bs], pos % bs)``.
  Idle lanes and write-masked pad positions scatter to the reserved
  trash block, which the length mask guarantees is never read;
- sampling happens inside the jitted tick with per-lane RNG keys,
  temperatures and top-k — greedy lanes (temperature 0) take the
  on-device argmax, so mixed greedy/sampled batches share one program.

Prefix cache (serve/lm/prefix.py): admission walks the prompt's full
token blocks through a radix trie and ADOPTS every cached match by
reference (per-block refcounts in kv.py), so chunked prefill starts at
the first miss; retiring requests hand their full prompt blocks to the
cache instead of freeing them, and the cache yields blocks back (LRU,
leaves first) only under pool pressure.

Preemption: when the pool is exhausted and a strictly higher-priority
tenant (TenantQoS priority classes via the ``tenant_priority`` hook) is
waiting, the lowest-priority active lane is swapped out — its written
KV blocks copied to a bounded host-side store (or, past the swap
budget, dropped for recompute), its stream PAUSED (no CLOSE, no error)
— and swapped back in once blocks free up, byte-exact with an
unpreempted run on the swap path.

Safety of block recycling: device dispatches from the scheduler thread
execute in dispatch order on one stream, so a stale in-flight tick's
scatter into a freed block always lands before the block's next owner
writes (and every position the next owner ever *reads* is one its own
later dispatches wrote).  Cached blocks extend the argument: no program
ever WRITES a cached block — decode writes at ``pos >= prompt_len`` and
prefill writes at ``pos >= adopted_start``, both past the full-prompt-
block region the cache holds — so adopting one is a pure read of
content whose producing dispatch already ordered before the adopter's.
"""

import functools
import queue
import threading
import time
from collections import OrderedDict, deque

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from client_tpu.serve.lm.kv import KvBlockPool
from client_tpu.serve.lm.policy import (
    LaneAutoscaler,
    bucket_for,
    chunk_plan,
    geometric_buckets,
    pad_prompt,
    verify_widths,
)
from client_tpu.serve.lm.prefix import PrefixCache
from client_tpu.serve.lm.spec import LaneSpec, SpecConfig
from client_tpu.serve.metrics import FLEET_HELP, LM_PREFIX_HELP, LM_SPEC_HELP
from client_tpu.serve.models.transformer import (
    _ffn_block,
    _mm,
    _rms_norm,
    _rope,
    lm_flops_per_token,
    paged_attention,
)
from client_tpu.serve.prof import NULL_TICK, PhaseProfiler

# sentinel object closing a stream's token queue
_CLOSE = object()

# placed-marker for a handle cancelled while its prefill job was in
# flight (chunks dispatch outside _cv); the job step sees it, frees the
# reservation and closes the queue
_CANCELLED = object()

# static cap for the per-lane top-k filter (per-lane k is dynamic below it)
_TOPK_CAP = 64

_LANE_HELP = {
    "ctpu_lm_lanes": "Configured decode lane count (autoscaled)",
    "ctpu_lm_active_lanes": "Decode lanes currently streaming",
}


def _select_token(logits, key, temperature, top_k):
    """One lane's token choice on device: argmax when temperature == 0,
    else temperature softmax sampling over the top-k filtered logits
    (top_k <= 0 = unfiltered)."""
    greedy = jnp.argmax(logits)
    kmax = min(_TOPK_CAP, logits.shape[-1])
    vals = lax.top_k(logits, kmax)[0]
    thresh = vals[jnp.clip(top_k - 1, 0, kmax - 1)]
    keep = (top_k <= 0) | (logits >= thresh)
    filtered = jnp.where(keep, logits, -jnp.inf)
    sampled = jax.random.categorical(
        key, filtered / jnp.maximum(temperature, 1e-6)
    )
    return jnp.where(temperature > 0.0, sampled, greedy).astype(jnp.int32)


def _decode_tick(params, tokens_full, pool_k, pool_v, tables, lens,
                 temps, topks, keys_full, *, cfg, n, block_size):
    """One batched decode step over the first ``n`` lanes (n is static:
    one executable per configured lane count)."""
    pool_k = list(pool_k)
    pool_v = list(pool_v)
    tok = tokens_full[:n]
    x = jnp.take(params["embed"], tok, axis=0)[:, None, :]  # [n,1,D]
    pos = lens  # [n]
    hd = cfg.head_dim
    lane = jnp.arange(n)
    blk_col = pos // block_size
    slot = pos % block_size
    for i, layer in enumerate(params["layers"]):
        h = _rms_norm(x, layer["ln_attn"])
        q = _mm(h, layer["attn"]["wq"]).reshape(n, 1, cfg.n_heads, hd)
        k = _mm(h, layer["attn"]["wk"]).reshape(n, 1, cfg.n_kv_heads, hd)
        v = _mm(h, layer["attn"]["wv"]).reshape(n, 1, cfg.n_kv_heads, hd)
        q = _rope(q, pos[:, None], cfg.rope_theta)
        k = _rope(k, pos[:, None], cfg.rope_theta)
        blk = tables[lane, blk_col]  # [n] physical block per lane
        pool_k[i] = pool_k[i].at[blk, slot].set(k[:, 0])
        pool_v[i] = pool_v[i].at[blk, slot].set(v[:, 0])
        attn = paged_attention(
            q, pool_k[i], pool_v[i], tables, pos[:, None], cfg, block_size
        )
        out = _mm(
            attn.reshape(n, 1, cfg.n_heads * hd), layer["attn"]["wo"]
        )
        x = x + out.astype(x.dtype)
        x, _ = _ffn_block(layer, x, cfg)
    x = _rms_norm(x, params["ln_f"])
    logits = _mm(x[:, 0], params["lm_head"]).astype(jnp.float32)  # [n,V]
    pairs = jax.vmap(functools.partial(jax.random.split, num=2))(
        keys_full[:n]
    )
    nxt = jax.vmap(_select_token)(logits, pairs[:, 0], temps, topks)
    tokens_out = tokens_full.at[:n].set(nxt)
    keys_out = keys_full.at[:n].set(pairs[:, 1])
    return tokens_out, pool_k, pool_v, keys_out


def _accept_lane(logits, props, count, temp, top_k, keys, *, width):
    """One lane's speculative acceptance rule on device.

    ``logits`` [w, V] are the target model's scores at positions
    ``length .. length + w - 1`` (position j scores the token FOLLOWING
    ``seq[j]``), ``props`` [w - 1] the drafted tokens (``props[j]`` is
    the proposal for what position j generates), ``count`` how many are
    real, ``keys`` [w + 1, 2] this lane's per-position RNG subkeys.

    Greedy lanes (temperature 0) accept a draft iff it equals the
    argmax — the accepted prefix + the argmax correction reconstructs
    plain greedy decode byte-exactly.  Temperature lanes run rejection
    sampling for a point-mass proposal: accept draft ``x`` with
    probability ``p(x)`` under the lane's filtered/tempered target
    distribution (the exact `_select_token` distribution), and on
    rejection sample the correction from the residual (``p`` with
    ``x``'s mass removed, renormalized) — the delivered tokens are an
    exact draw from the target distribution.  When every draft is
    accepted the correction is a free "bonus" sample from the last
    position's full distribution.

    Returns (n_accepted, correction_token).
    """
    w = width
    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1)  # [w]
    kmax = min(_TOPK_CAP, vocab)
    vals = lax.top_k(logits, kmax)[0]
    thresh = vals[:, jnp.clip(top_k - 1, 0, kmax - 1)]
    keep = (top_k <= 0) | (logits >= thresh[:, None])
    scaled = jnp.where(keep, logits, -jnp.inf) / jnp.maximum(temp, 1e-6)
    probs = jax.nn.softmax(scaled, axis=-1)  # [w, V] target distribution
    j = jnp.arange(w - 1)
    p_draft = probs[j, props]
    u = jax.vmap(jax.random.uniform)(keys[:w - 1])
    accept = jnp.where(temp > 0.0, u < p_draft, props == greedy[:w - 1])
    # longest accepted prefix of the REAL drafts (cumprod stops at the
    # first rejection; padding past ``count`` never counts)
    chain = jnp.cumprod(
        jnp.where(j < count, accept, False).astype(jnp.int32)
    )
    n_acc = jnp.sum(chain).astype(jnp.int32)
    rejected = n_acc < count
    rej_tok = props[jnp.minimum(n_acc, w - 2)]
    corr_scaled = jnp.where(
        rejected & (jnp.arange(vocab) == rej_tok), -jnp.inf,
        scaled[n_acc],
    )
    sampled = jax.random.categorical(keys[w - 1], corr_scaled)
    corr = jnp.where(temp > 0.0, sampled, greedy[n_acc])
    return n_acc, corr.astype(jnp.int32)


def _verify_tick(params, tokens_full, pool_k, pool_v, tables, lens,
                 temps, topks, keys_full, props, counts, *, cfg, n,
                 width, block_size):
    """One speculative verify step over the first ``n`` lanes: embed the
    pending input token plus up to ``width - 1`` drafted tokens per lane
    and score all of them in ONE multi-position paged-attention pass
    (``paged_attention`` already handles [n, T] query positions — this
    is ``_decode_tick`` generalized from T = 1 to T = width).

    K/V for every drafted position scatters into the lane's own block
    reservation as it is computed (position ``lens + j`` attends only
    positions ``<= lens + j``, all of which this tick or history wrote),
    so accepted positions need no second write.  Positions past the
    lane's draft count write to the trash block (the prefill padding
    trick); positions past the ACCEPTED prefix hold garbage the length
    mask never reads — the host advances ``lane.length`` only to the
    accepted end, and the next tick overwrites from there.  Rejection
    therefore "rewinds" by pointer arithmetic alone: no block ever
    leaves the lane's reservation, so nothing can leak.

    Returns ``(out, tokens_out, pool_k, pool_v, keys_out)`` where
    ``out`` is ``[2, n]`` (accepted count, correction token) — one
    host readback for the whole tick.  ``n`` and ``width`` are static:
    executables stay ``<= len(verify_widths) * len(lane_counts)``.
    """
    pool_k = list(pool_k)
    pool_v = list(pool_v)
    w = width
    seq = jnp.concatenate([tokens_full[:n, None], props], axis=1)  # [n,w]
    x = jnp.take(params["embed"], seq, axis=0)  # [n,w,D]
    pos = lens[:, None] + jnp.arange(w)[None, :]  # [n,w]
    writable = jnp.arange(w)[None, :] <= counts[:, None]
    hd = cfg.head_dim
    col = jnp.minimum(pos // block_size, tables.shape[1] - 1)
    blk = jnp.where(
        writable, jnp.take_along_axis(tables, col, axis=1),
        KvBlockPool.TRASH,
    )
    slot = pos % block_size
    for i, layer in enumerate(params["layers"]):
        h = _rms_norm(x, layer["ln_attn"])
        q = _mm(h, layer["attn"]["wq"]).reshape(n, w, cfg.n_heads, hd)
        k = _mm(h, layer["attn"]["wk"]).reshape(n, w, cfg.n_kv_heads, hd)
        v = _mm(h, layer["attn"]["wv"]).reshape(n, w, cfg.n_kv_heads, hd)
        q = _rope(q, pos, cfg.rope_theta)
        k = _rope(k, pos, cfg.rope_theta)
        pool_k[i] = pool_k[i].at[blk, slot].set(k)
        pool_v[i] = pool_v[i].at[blk, slot].set(v)
        attn = paged_attention(
            q, pool_k[i], pool_v[i], tables, pos, cfg, block_size
        )
        out = _mm(
            attn.reshape(n, w, cfg.n_heads * hd), layer["attn"]["wo"]
        )
        x = x + out.astype(x.dtype)
        x, _ = _ffn_block(layer, x, cfg)
    x = _rms_norm(x, params["ln_f"])
    logits = _mm(x, params["lm_head"]).astype(jnp.float32)  # [n,w,V]
    keys = jax.vmap(functools.partial(jax.random.split, num=w + 1))(
        keys_full[:n]
    )  # [n, w+1, 2]: w-1 accept draws, 1 correction sample, 1 carry
    n_acc, corr = jax.vmap(
        functools.partial(_accept_lane, width=w)
    )(logits, props, counts, temps, topks, keys)
    tokens_out = tokens_full.at[:n].set(corr)
    keys_out = keys_full.at[:n].set(keys[:, w])
    out = jnp.stack([n_acc, corr])  # [2, n]: one readback per tick
    return out, tokens_out, pool_k, pool_v, keys_out


def _prefill_chunk(params, chunk, pool_k, pool_v, table, start,
                   prompt_len, key, temperature, top_k, *, cfg,
                   block_size):
    """One prefill chunk ([1, C] tokens at logical positions
    start..start+C-1) written straight into the paged pool.

    Positions >= prompt_len (bucket padding) write to the trash block
    and are never attended (the length mask), so padding is inert.  The
    returned token is the sampled/greedy first generation token — only
    the FINAL chunk's return is meaningful (its chunk contains position
    prompt_len - 1)."""
    pool_k = list(pool_k)
    pool_v = list(pool_v)
    c = chunk.shape[1]
    x = jnp.take(params["embed"], chunk, axis=0)  # [1,C,D]
    pos = start + jnp.arange(c)  # [C] logical positions
    writable = pos < prompt_len
    hd = cfg.head_dim
    blk = jnp.where(
        writable, table[pos // block_size], KvBlockPool.TRASH
    )
    slot = pos % block_size
    for i, layer in enumerate(params["layers"]):
        h = _rms_norm(x, layer["ln_attn"])
        q = _mm(h, layer["attn"]["wq"]).reshape(1, c, cfg.n_heads, hd)
        k = _mm(h, layer["attn"]["wk"]).reshape(1, c, cfg.n_kv_heads, hd)
        v = _mm(h, layer["attn"]["wv"]).reshape(1, c, cfg.n_kv_heads, hd)
        q = _rope(q, pos[None, :], cfg.rope_theta)
        k = _rope(k, pos[None, :], cfg.rope_theta)
        pool_k[i] = pool_k[i].at[blk, slot].set(k[0])
        pool_v[i] = pool_v[i].at[blk, slot].set(v[0])
        attn = paged_attention(
            q, pool_k[i], pool_v[i], table[None], pos[None], cfg,
            block_size,
        )
        out = _mm(
            attn.reshape(1, c, cfg.n_heads * hd), layer["attn"]["wo"]
        )
        x = x + out.astype(x.dtype)
        x, _ = _ffn_block(layer, x, cfg)
    x = _rms_norm(x, params["ln_f"])
    last = jnp.clip(prompt_len - 1 - start, 0, c - 1)
    xsel = jnp.take(x, last[None], axis=1)  # [1,1,D]
    logits = _mm(xsel[:, 0], params["lm_head"]).astype(jnp.float32)[0]
    k_sample, k_carry = jax.random.split(key)
    tok = _select_token(logits, k_sample, temperature, top_k)
    return tok, pool_k, pool_v, k_carry


def _adopt(tokens, keys, slot, tok, key):
    """Install an admitted request's first token + RNG carry into lane
    ``slot`` (traced index: one executable regardless of slot)."""
    return tokens.at[slot].set(tok), keys.at[slot].set(key)


class _Lane:
    __slots__ = ("gen", "active", "queue", "remaining", "produced",
                 "length", "limit", "tenant", "temperature", "top_k",
                 "table", "blocks", "prompt", "tokens", "handle", "spec")

    def __init__(self, table_width):
        self.gen = 0        # bumped on every (re)assignment and cancel
        self.active = False
        self.queue = None
        self.remaining = 0
        self.produced = 0
        self.length = 0     # logical sequence length (next write position)
        self.limit = 0      # prompt_len + max_tokens: last writable pos + 1
        self.tenant = ""
        self.temperature = 0.0
        self.top_k = 0
        self.table = np.zeros((table_width,), np.int32)  # trash-filled
        self.blocks = None  # reservation owned while active
        self.prompt = None  # [1, T] prompt row (prefix-cache insertion)
        self.tokens = []    # delivered generation tokens (recompute replay)
        self.handle = None  # the submit() handle streaming on this lane
        self.spec = None    # LaneSpec when speculative decoding is on


class _Handle:
    """Opaque submit() handle; ``placed`` is None (pending / mid-prefill),
    _CANCELLED, or (slot, gen) once streaming."""

    __slots__ = ("prompt", "prompt_len", "max_tokens", "queue", "tenant",
                 "temperature", "top_k", "seed", "placed", "remote_kv")

    def __init__(self, prompt, max_tokens, q, tenant, temperature, top_k,
                 seed):
        self.prompt = prompt
        self.prompt_len = int(prompt.shape[1])
        self.max_tokens = int(max_tokens)
        self.queue = q
        self.tenant = tenant
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.placed = None
        # (covered_blocks, host_k, host_v) fetched from the fleet prefix
        # tier on the submit caller's thread (never under _cv); _admit
        # adopts whatever still beats the local trie at admission time
        self.remote_kv = None


class _PrefillJob:
    __slots__ = ("handle", "slot", "blocks", "table", "plan", "chunk_idx",
                 "key", "token", "resume", "remote")

    def __init__(self, handle, slot, blocks, table, plan, key):
        self.handle = handle
        self.slot = slot
        self.blocks = blocks
        self.table = table
        self.plan = plan
        self.chunk_idx = 0
        self.key = key
        self.token = None
        # _Swapped being resumed via the recompute path (None for normal
        # admissions): activation restores its produced/remaining state
        # and the saved token/RNG carry instead of the chunk's sample
        self.resume = None
        # [lo, hi, host_k, host_v]: fleet-fetched KV content destined for
        # blocks[lo:hi]; installed by the FIRST _prefill_step (outside
        # _cv), cleared once on device — abort before install must not
        # cache those blocks as valid content
        self.remote = None


class _Swapped:
    """One preempted stream parked off-device.

    ``host_k``/``host_v`` hold the lane's written blocks per layer when
    the swap fit the host budget; None means the recompute path (replay
    prompt + delivered tokens through chunked prefill on resume).  The
    stream's queue is PAUSED — no CLOSE, no error — until resume or
    cancel."""

    __slots__ = ("handle", "queue", "tenant", "prompt", "prompt_len",
                 "produced", "remaining", "length", "limit", "temperature",
                 "top_k", "tokens", "token", "key", "host_k", "host_v",
                 "n_blocks", "written_blocks", "cancelled", "t_swap")

    def __init__(self, lane, n_blocks, written_blocks, token, key,
                 host_k, host_v):
        self.handle = lane.handle
        self.queue = lane.queue
        self.tenant = lane.tenant
        self.prompt = lane.prompt
        self.prompt_len = int(lane.prompt.shape[1])
        self.produced = lane.produced
        self.remaining = lane.remaining
        self.length = lane.length
        self.limit = lane.limit
        self.temperature = lane.temperature
        self.top_k = lane.top_k
        self.tokens = list(lane.tokens)
        self.token = token          # input token for the next decode tick
        self.key = key              # RNG carry at the preemption point
        self.host_k = host_k
        self.host_v = host_v
        self.n_blocks = int(n_blocks)
        self.written_blocks = int(written_blocks)
        self.cancelled = False
        self.t_swap = time.monotonic()


class LmEngine:
    """Continuous-batching decode engine (submit/cancel/close surface
    compatible with the old ContinuousLmScheduler).

    ``submit(prompt_tokens, max_tokens, temperature=0, top_k=0, seed=0,
    tenant="")`` returns ``(queue, handle)``; the queue yields int token
    ids and finally :data:`CLOSE`.  ``cancel(handle)`` releases a stream
    early.  Device state (KV pool, lane arrays, scheduler thread)
    allocates lazily on the first submit so an idle engine pins no HBM.
    """

    CLOSE = _CLOSE

    def __init__(self, params, cfg, max_slots=8, lane_counts=None,
                 block_size=16, pool_tokens=None, prefill_chunk=None,
                 min_bucket=16, readback_depth=8, eos_id=None,
                 check_prompt=None, registry=None, tracer=None,
                 tenant_lane_share=0.75, scale_up_after=3,
                 scale_down_after=50, tick_log_len=8192,
                 prefix_cache=True, min_prefix_blocks=1,
                 tenant_priority=None, swap_block_limit=None, fleet=None,
                 speculative=None):
        self.params = params
        self.cfg = cfg
        self.max_slots = int(max_slots)
        if lane_counts is None:
            lane_counts = sorted({
                max(1, self.max_slots // 4),
                max(1, self.max_slots // 2),
                self.max_slots,
            })
        self.lane_counts = tuple(sorted(set(int(c) for c in lane_counts)))
        if self.lane_counts[-1] != self.max_slots:
            raise ValueError("largest lane count must equal max_slots")
        self.depth = max(int(readback_depth), 0)
        self.eos_id = eos_id
        self.check_prompt = check_prompt  # optional prompt validator
        self.registry = registry
        self.tracer = tracer
        # flight recorder (serve/flight.py; bound by the model binder):
        # preemptions and a wedged scheduler loop land in the server's
        # postmortem ring — a wedge also dumps it automatically
        self.flight = None
        self.tenant_lane_share = tenant_lane_share
        self.block_size = int(block_size)
        chunk = int(prefill_chunk or min(64, cfg.max_seq))
        self.buckets = geometric_buckets(
            min(min_bucket, chunk), min(chunk, cfg.max_seq)
        )
        self._table_width = -(-cfg.max_seq // self.block_size)
        self._pool_tokens = int(pool_tokens or self.max_slots * cfg.max_seq)

        self._cv = threading.Condition()
        self._closed = False
        self._lanes = [
            _Lane(self._table_width) for _ in range(self.max_slots)
        ]
        self._pending = OrderedDict()  # tenant -> deque[_Handle]
        self._rr = 0                   # round-robin cursor over tenants
        self._job = None
        self._scaler = LaneAutoscaler(
            self.lane_counts, up_after=scale_up_after,
            down_after=scale_down_after,
        )
        self._tick_log = deque(maxlen=int(tick_log_len))
        self._inflight = deque()
        self._thread = None  # started lazily on the first submit

        # continuous profiler (serve/prof.py): each scheduler pass is one
        # tick with schedule/dispatch/device-wait/delivery phase spans;
        # the model binder rebinds the registry and adopts this profiler
        # into the server's, so /v2/debug/prof and flight dumps cover
        # the LM engine too.  _ptick is the scheduler thread's current
        # tick — only that thread ever touches it.
        self.prof = PhaseProfiler(name="lm", registry=registry)
        self._ptick = NULL_TICK
        self._flops_per_token = lm_flops_per_token(cfg)

        # prefix cache + preemption state
        self._prefix_enabled = bool(prefix_cache)
        self.min_prefix_blocks = int(min_prefix_blocks)
        # tenant -> priority (callable or mapping; None/absent = 0.0) —
        # preemption triggers only for a STRICTLY higher-priority waiter
        self.tenant_priority = tenant_priority
        # host-side swap store budget in blocks (None = one pool's worth)
        self.swap_block_limit = swap_block_limit
        self._swapped = []          # paused _Swapped streams, FIFO
        self._swapped_blocks = 0    # blocks parked in the host store
        self._preempt = None        # (slot, gen) chosen by _admit
        self._preemptions = 0
        self._resume_ms = []        # swap-out -> reactivation latencies

        # fleet prefix tier (serve/fleet.py): peer lookups run on the
        # SUBMIT caller's thread and exports on the scheduler thread,
        # both strictly outside _cv (the PEER-CALL-UNDER-LOCK gate)
        self.fleet = fleet
        self._fleet_lookups = 0     # peer prefix lookups issued
        self._fleet_blocks = 0      # blocks installed from peers

        # device state allocates lazily with the thread
        self.kv = None
        self.prefix = None
        self._tokens = None
        self._keys = None
        # donate the KV pool buffers (args 2/3 of both programs): the
        # functional .at[].set update would otherwise materialize a full
        # copy of every per-layer block pool on EACH dispatch — ~2x the
        # dominant HBM allocation and a whole-pool copy per token.  The
        # caller reassigns self.kv.pools from the outputs immediately, so
        # the donated inputs are never touched again.  CPU (the test
        # platform) has no donation support; jit would just warn.
        self._donate = (
            (2, 3) if jax.default_backend() != "cpu" else ()
        )
        self._prefill = jax.jit(
            functools.partial(
                _prefill_chunk, cfg=cfg, block_size=self.block_size
            ),
            donate_argnums=self._donate,
        )
        self._adopt = jax.jit(_adopt)
        self._tick_jits = {}

        # speculative decoding (serve/lm/spec.py; off by default): the
        # drafter + adaptive-k policy is per-model config, the verify
        # widths a fixed geometric set so the verify executable count is
        # provably <= len(_verify_widths) * len(lane_counts)
        self._spec = SpecConfig.parse(speculative)
        self._verify_widths = (
            verify_widths(self._spec.k) if self._spec is not None else ()
        )
        self._verify_jits = {}
        self._spec_proposed = 0
        self._spec_accepted = 0

    # -- executable accounting (the bounded-compile proofs) ---------------

    def prefill_executables(self):
        """Compiled prefill-chunk executable count (<= len(self.buckets)
        by construction — chunk widths come from the bucket set)."""
        size = getattr(self._prefill, "_cache_size", None)
        return size() if callable(size) else None

    def decode_executables(self):
        """Compiled decode-tick executable count (<= len(lane_counts))."""
        with self._cv:  # the scheduler inserts into _tick_jits mid-run
            fns = list(self._tick_jits.values())
        total = 0
        for fn in fns:
            size = getattr(fn, "_cache_size", None)
            total += size() if callable(size) else 1
        return total

    def verify_executables(self):
        """Compiled speculative-verify executable count
        (<= len(verify_widths(k)) * len(lane_counts) by construction)."""
        with self._cv:  # the scheduler inserts into _verify_jits mid-run
            fns = list(self._verify_jits.values())
        total = 0
        for fn in fns:
            size = getattr(fn, "_cache_size", None)
            total += size() if callable(size) else 1
        return total

    def spec_stats(self):
        """Speculative-decoding counters ({} when speculation is off)."""
        with self._cv:
            if self._spec is None:
                return {}
            prop, acc = self._spec_proposed, self._spec_accepted
            return {
                "proposed": prop,
                "accepted": acc,
                "rejected": prop - acc,
                "acceptance_rate": round(acc / max(prop, 1), 4),
            }

    def tick_trace(self):
        """Recent per-tick records ({kind, t0, t1, lanes, n_lanes}) —
        the fairness/jitter evidence tests and ops read."""
        with self._cv:
            return list(self._tick_log)

    def prefix_stats(self):
        """Prefix-cache counters ({} when the cache is disabled or the
        engine never started)."""
        with self._cv:
            return {} if self.prefix is None else self.prefix.stats()

    def preempt_stats(self):
        """Preemption/swap counters: preemptions, completed resumes with
        their swap-out -> reactivation latencies, streams still parked."""
        with self._cv:
            return {
                "preemptions": self._preemptions,
                "resumes": len(self._resume_ms),
                "resume_ms": list(self._resume_ms),
                "swapped_streams": len(self._swapped),
                "swapped_blocks": self._swapped_blocks,
            }

    def set_registry(self, registry):
        """Late-bind the serving metrics registry (add_model wiring)."""
        with self._cv:
            self.registry = registry
            if self.prefix is not None:
                self.prefix.registry = registry
            kv = self.kv
        if kv is not None:
            kv.set_registry(registry)
        self.prof.set_registry(registry)

    def set_fleet(self, fleet):
        """Late-bind the cross-replica prefix tier (add_model wiring):
        submit consults it on local-trie shortfall, prefill completion
        exports into it, drain migrates parked streams through it."""
        with self._cv:
            self.fleet = fleet

    def fleet_stats(self):
        """Fleet prefix-tier counters: peer lookups issued at submit and
        KV blocks installed from peers (zeros when no tier is bound)."""
        with self._cv:
            return {
                "remote_lookups": self._fleet_lookups,
                "remote_blocks": self._fleet_blocks,
            }

    def pressure(self):
        """Autoscaling signal: queued submissions + parked (swapped)
        streams + active lanes — the LM half of the per-replica
        queue-depth gauge the fleet tier gossips on probes — plus
        paged-KV occupancy (block exhaustion is the earliest scale-up
        signal for LM workloads)."""
        with self._cv:
            pending = sum(len(dq) for dq in self._pending.values())
            active = sum(1 for lane in self._lanes if lane.active)
            kv = self.kv
        # KV accounting outside the condition lock: the pool has its own
        # synchronization and holding _cv across it invites lock nesting
        kv_fraction = 0.0
        if kv is not None:
            used = kv.used_blocks
            total = used + kv.free_blocks
            kv_fraction = round(used / total, 4) if total > 0 else 0.0
        return {
            "queue_depth": pending + len(self._swapped),
            "inflight": active,
            "kv_used_fraction": kv_fraction,
        }

    # -- request side ------------------------------------------------------

    def submit(self, prompt_tokens, max_tokens, temperature=0.0, top_k=0,
               seed=0, tenant=""):
        """Returns (token_queue, handle); the queue ends with CLOSE."""
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        max_tokens = min(int(max_tokens),
                         self.cfg.max_seq - prompt.shape[1])
        q = queue.Queue()
        if max_tokens <= 0:
            q.put(_CLOSE)
            return q, None
        handle = _Handle(prompt, max_tokens, q, str(tenant or ""),
                         temperature, top_k, seed)
        fleet = self.fleet
        if fleet is not None and self._prefix_enabled:
            shareable = (handle.prompt_len - 1) // self.block_size
            if shareable > 0:
                with self._cv:
                    if self._closed:
                        q.put(_CLOSE)
                        return q, None
                    self._ensure_thread_locked()
                    local = len(
                        self.prefix.match(handle.prompt[0], shareable)[0]
                    )
                    if local < shareable:
                        self._fleet_lookups += 1
                if local < shareable:
                    # peer RPC on the CALLER's thread with no engine lock
                    # held: a slow/dead peer delays only this submit, by
                    # at most the tier's bounded fan-out x timeout — the
                    # scheduler keeps ticking throughout.  Only the tail
                    # past the local match travels (start_blocks).
                    got = fleet.prefix_lookup(
                        handle.prompt[0], self.block_size, shareable,
                        start_blocks=local,
                    )
                    if got is not None and got[0] > local:
                        handle.remote_kv = got
        with self._cv:
            if self._closed:
                q.put(_CLOSE)
                return q, None
            self._ensure_thread_locked()
            self._pending.setdefault(handle.tenant, deque()).append(handle)
            self._cv.notify_all()
        return q, handle

    def cancel(self, handle):
        """Release a stream early (consumer went away)."""
        if handle is None:
            return
        with self._cv:
            lane_q = self._pending.get(handle.tenant)
            if lane_q is not None:
                for i, entry in enumerate(lane_q):
                    if entry is handle:
                        entry.queue.put(_CLOSE)
                        del lane_q[i]
                        if not lane_q:
                            del self._pending[handle.tenant]
                        return
            placed = handle.placed
            if placed is None:
                # popped from pending but not yet streaming: the prefill
                # job is mid-dispatch outside _cv.  Mark the handle; the
                # job step aborts and closes the queue.
                handle.placed = _CANCELLED
                return
            if placed is _CANCELLED:
                return
            if isinstance(placed, _Swapped):
                # preempted and parked: its blocks were already released
                # at swap-out, so cancel just closes the paused stream
                # and drops the host copies (a resume job in flight for
                # it sees .cancelled and aborts)
                if not placed.cancelled:
                    placed.cancelled = True
                    placed.queue.put(_CLOSE)
                    if placed in self._swapped:
                        self._swapped.remove(placed)
                        if placed.host_k is not None:
                            self._swapped_blocks -= placed.written_blocks
                            self._swap_gauge_locked()
                    placed.host_k = placed.host_v = None
                return
            slot_idx, gen = placed
            lane = self._lanes[slot_idx]
            if lane.active and lane.gen == gen:
                self._retire_lane_locked(lane)

    def close(self):
        with self._cv:
            self._closed = True
            self._release_all_locked()
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)

    # -- locked helpers ----------------------------------------------------

    def _ensure_thread_locked(self):
        if self._thread is not None:
            return
        self.kv = KvBlockPool(
            self.cfg,
            n_blocks=max(
                self._pool_tokens // self.block_size, self._table_width
            ),
            block_size=self.block_size,
            registry=self.registry,
        )
        if self._prefix_enabled:
            self.prefix = PrefixCache(
                self.kv, registry=self.registry,
                min_prefix_blocks=self.min_prefix_blocks,
            )
        if self.swap_block_limit is None:
            self.swap_block_limit = self.kv.n_blocks
        self._tokens = jnp.zeros((self.max_slots,), jnp.int32)
        self._keys = jnp.zeros((self.max_slots, 2), jnp.uint32)
        self._thread = threading.Thread(
            target=self._loop, name="lm-engine", daemon=True
        )
        self._thread.start()

    def _retire_lane_locked(self, lane, close_queue=True):
        """Release a lane and return its KV reservation (full prompt
        blocks go to the prefix cache; the rest free).  ``close_queue``
        False is the preemption path: the stream pauses, it does not
        end."""
        lane.active = False
        lane.gen += 1  # in-flight ticks for this lane drop on drain
        if close_queue:
            lane.queue.put(_CLOSE)
        lane.table[:] = KvBlockPool.TRASH
        written, lane.length = lane.length, 0
        prompt, lane.prompt = lane.prompt, None
        lane.tokens = []
        lane.handle = None
        lane.spec = None
        blocks, lane.blocks = lane.blocks, None
        if blocks:
            self._release_blocks_locked(prompt, written, blocks)

    def _release_blocks_locked(self, prompt, written_tokens, blocks):
        """Return one reservation: fully written FULL prompt blocks are
        offered to the prefix cache (the holder's reference transfers or
        drops — see PrefixCache.give_back), everything else frees."""
        if self.prefix is None or prompt is None:
            self.kv.release(blocks)
            return
        prompt_row = prompt[0]
        cacheable = (
            min(int(written_tokens), prompt_row.shape[0]) // self.block_size
        )
        self.prefix.give_back(prompt_row, cacheable, blocks)

    def _release_all_locked(self):
        """Close every pending/active/in-prefill/swapped stream and drop
        the warm cache (caller holds _cv)."""
        for lane_q in self._pending.values():
            for entry in lane_q:
                entry.queue.put(_CLOSE)
        self._pending.clear()
        for lane in self._lanes:
            if lane.active:
                self._retire_lane_locked(lane)
        job, self._job = self._job, None
        if job is not None:
            self._abort_job_locked(job)
        swapped, self._swapped = self._swapped, []
        for entry in swapped:
            if not entry.cancelled:
                entry.cancelled = True
                entry.queue.put(_CLOSE)
        self._swapped_blocks = 0
        self._preempt = None
        if self.prefix is not None:
            # AFTER every give_back above: the pool must end fully free
            self.prefix.clear()

    def _abort_job_locked(self, job):
        blocks, job.blocks = job.blocks, None
        if blocks:
            # chunks already dispatched cover positions up to the next
            # chunk's start; those full prompt blocks are valid cache
            # content even though the request died mid-prefill
            written = (
                job.plan[job.chunk_idx][0]
                if job.chunk_idx < len(job.plan)
                else job.handle.prompt_len
            )
            if job.remote is not None:
                # fleet-fetched blocks were never installed on device:
                # only the locally adopted prefix below them is real
                # content — caching the uninstalled range would poison
                # the trie with garbage KV
                written = min(written, job.remote[0] * self.block_size)
            self._release_blocks_locked(job.handle.prompt, written, blocks)
        if job.resume is not None:
            if not job.resume.cancelled:
                job.resume.cancelled = True
                job.resume.queue.put(_CLOSE)
        else:
            job.handle.queue.put(_CLOSE)

    def _tenant_lanes_locked(self, tenant):
        held = sum(
            1 for lane in self._lanes if lane.active and lane.tenant == tenant
        )
        if self._job is not None and self._job.handle.tenant == tenant:
            held += 1
        return held

    def _tenant_quota_locked(self, tenant, n_lanes, others_pending):
        """Max lanes *tenant* may hold right now.  Work-conserving: the
        quota binds only while another tenant is waiting."""
        if not others_pending:
            return n_lanes
        share = self.tenant_lane_share
        if callable(share):
            share = share(tenant)
        if share is None:
            share = 1.0
        return max(1, min(n_lanes, int(np.ceil(float(share) * n_lanes))))

    def _pick_pending_locked(self, n_lanes):
        """Pop the next admissible pending handle: strict priority-class
        order first (a gold request queued behind a backpressured bronze
        one must be picked — and preempt — FIRST, or pool exhaustion
        re-picks the bronze head forever and preemption never fires),
        round-robin-fair within a class (the only order when no
        priorities are configured); tenants at their lane quota are
        skipped while others wait."""
        tenants = [t for t, dq in self._pending.items() if dq]
        if not tenants:
            return None
        rotated = tenants[self._rr % len(tenants):] + \
            tenants[:self._rr % len(tenants)]
        # stable sort: equal classes keep their rotated (rr) order
        order = sorted(rotated, key=lambda t: -self._priority_of(t))
        for tenant in order:
            others = any(t != tenant and dq for t, dq in
                         self._pending.items() if dq)
            quota = self._tenant_quota_locked(tenant, n_lanes, others)
            if self._tenant_lanes_locked(tenant) >= quota:
                continue
            self._rr += 1
            lane_q = self._pending[tenant]
            handle = lane_q.popleft()
            if not lane_q:
                # a drained tenant's entry is evicted: client-minted
                # tenant ids must not grow the map (or the per-pass
                # scan) without bound
                del self._pending[tenant]
            return handle
        return None

    def _max_active_locked(self):
        top = -1
        for i, lane in enumerate(self._lanes):
            if lane.active:
                top = i
        if self._job is not None:
            top = max(top, self._job.slot)
        return top

    def _queued_locked(self):
        return any(dq for dq in self._pending.values())

    def _has_pending_locked(self):
        # swapped streams count as pending pressure: they need a lane and
        # blocks to resume, so the autoscaler must not scale down past them
        return self._queued_locked() or bool(self._swapped)

    def _priority_of(self, tenant):
        """Priority class of *tenant* (higher preempts lower; default 0)."""
        source = self.tenant_priority
        if source is None:
            return 0.0
        value = source(tenant) if callable(source) else source.get(tenant)
        return 0.0 if value is None else float(value)

    def _pick_preempt_victim_locked(self, tenant):
        """Lowest-priority active lane STRICTLY below *tenant*'s class
        (ties broken toward the shortest sequence — least KV to swap);
        None when nothing qualifies."""
        want = self._priority_of(tenant)
        victim = None
        victim_key = None
        for i, lane in enumerate(self._lanes):
            if not lane.active:
                continue
            pri = self._priority_of(lane.tenant)
            if pri >= want:
                continue
            key = (pri, lane.length)
            if victim_key is None or key < victim_key:
                victim, victim_key = i, key
        return victim

    def _restore_lane_locked(self, lane, entry, slot):
        """Install a parked _Swapped stream's saved counters/identity on
        a lane and stamp the resume latency.  The caller owns gen/active
        and the table/blocks install — those differ between the swap-in
        and recompute-replay paths."""
        lane.queue = entry.queue
        lane.remaining = entry.remaining
        lane.produced = entry.produced
        lane.length = entry.length
        lane.limit = entry.limit
        lane.tenant = entry.tenant
        lane.temperature = entry.temperature
        lane.top_k = entry.top_k
        lane.prompt = entry.prompt
        lane.tokens = list(entry.tokens)
        lane.handle = entry.handle
        # drafter state rebuilds from the prompt; the adaptive-k window
        # restarts (a resume is rare — one extra window to re-disable an
        # adversarial lane is noise)
        lane.spec = (
            LaneSpec(self._spec, entry.prompt[0])
            if self._spec is not None else None
        )
        if entry.handle is not None:
            entry.handle.placed = (slot, lane.gen)
        self._resume_ms.append((time.monotonic() - entry.t_swap) * 1e3)

    def _swap_gauge_locked(self):
        if self.registry is not None:
            self.registry.set(
                "ctpu_lm_swapped_blocks", None, self._swapped_blocks,
                help_=LM_PREFIX_HELP["ctpu_lm_swapped_blocks"],
            )

    def _lane_gauges_locked(self, active_count=None):
        if self.registry is None:
            return
        self.registry.set("ctpu_lm_lanes", None, self._scaler.n_lanes,
                          help_=_LANE_HELP["ctpu_lm_lanes"])
        if active_count is None:
            active_count = sum(1 for lane in self._lanes if lane.active)
        self.registry.set("ctpu_lm_active_lanes", None, active_count,
                          help_=_LANE_HELP["ctpu_lm_active_lanes"])

    # -- scheduler loop ----------------------------------------------------

    def _reserve_locked(self, needed, matched_blocks):
        """Allocate ``needed - len(matched)`` fresh blocks, evicting warm
        cache blocks under pressure.  Matched blocks must already be
        adopted (refcount >= 2) so eviction can never steal them.
        Returns the fresh list or None."""
        short = needed - len(matched_blocks)
        fresh = self.kv.alloc(short)
        if fresh is None and self.prefix is not None:
            missing = short - self.kv.free_blocks
            if self.prefix.evict(missing) >= missing:
                fresh = self.kv.alloc(short)
        return fresh

    def _admit(self):
        """Move one pending request into a prefill job (bookkeeping under
        _cv; every chunk dispatch happens later, outside the lock).
        Prefix-cache adoption happens here: matched prompt blocks are
        retained by reference and the chunk plan starts at the first
        miss."""
        with self._cv:
            if (self._closed or self._job is not None
                    or self._preempt is not None):
                return
            n_lanes = self._scaler.n_lanes
            slot = next(
                (i for i in range(n_lanes) if not self._lanes[i].active),
                None,
            )
            if slot is None:
                # every lane busy: ANY pending work is starvation —
                # sustained starvation steps the lane count up.  (Checked
                # before the quota-aware pick: a tenant at its lane quota
                # with zero free lanes must still register pressure.)
                if self._has_pending_locked():
                    if self._scaler.note_starved():
                        self._lane_gauges_locked()
                else:
                    self._scaler.note_ok(False, self._max_active_locked())
                return
            handle = self._pick_pending_locked(n_lanes)
            if handle is None:
                # nothing admissible: idle, or every pending tenant is at
                # its quota while a lane sits free (note_ok with pending
                # True so the free lane cannot drive a scale-down under a
                # quota-capped backlog)
                self._scaler.note_ok(
                    self._has_pending_locked(), self._max_active_locked()
                )
                self._lane_gauges_locked()
                return
            needed = self.kv.blocks_for(
                handle.prompt_len + handle.max_tokens
            )
            matched_blocks, matched_nodes = [], []
            shareable = (handle.prompt_len - 1) // self.block_size
            if self.prefix is not None and shareable:
                # cap at (prompt_len - 1): the final prompt position must
                # always prefill — its logits seed the first new token
                matched_blocks, matched_nodes = self.prefix.match(
                    handle.prompt[0], shareable
                )
                # adopt BEFORE the allocation attempt: refcount 2 pins the
                # matched chain against the eviction pass below
                self.prefix.adopt(matched_nodes)
            fresh = self._reserve_locked(needed, matched_blocks)
            if fresh is None:
                # pool exhausted even after cache eviction: drop the
                # adoption, then either preempt a strictly lower-priority
                # lane for a higher-priority waiter or backpressure until
                # completions free blocks.  (The pick may have evicted the
                # tenant's drained entry — recreate it.)
                if matched_blocks:
                    self.kv.release(matched_blocks)
                victim = self._pick_preempt_victim_locked(handle.tenant)
                if victim is not None:
                    self._preempt = (victim, self._lanes[victim].gen)
                self._pending.setdefault(
                    handle.tenant, deque()
                ).appendleft(handle)
                self._rr -= 1
                return
            blocks = matched_blocks + fresh
            table = np.full(
                (self._table_width,), KvBlockPool.TRASH, np.int32
            )
            table[:len(blocks)] = blocks
            start = len(matched_blocks) * self.block_size
            job_remote = None
            if handle.remote_kv is not None:
                # fleet-tier adoption beyond the local trie: blocks
                # [local..covered) are FRESH pool blocks whose content the
                # first _prefill_step installs from the peer's host arrays
                # (outside _cv); the chunk plan starts past them.  The
                # fetched arrays cover blocks [rstart, covered) — if the
                # trie shrank below rstart since the submit-time probe
                # (eviction under pressure), the fetch cannot bridge the
                # gap and is dropped: prefill is always correct, just
                # slower.
                covered = min(int(handle.remote_kv[0]), shareable)
                rstart = handle.remote_kv[3]
                if rstart <= len(matched_blocks) < covered:
                    job_remote = [
                        len(matched_blocks), covered,
                        handle.remote_kv[1], handle.remote_kv[2], rstart,
                    ]
                    start = covered * self.block_size
                    self._fleet_blocks += covered - len(matched_blocks)
                    if self.registry is not None:
                        self.registry.inc(
                            "ctpu_fleet_prefix_blocks_total", None,
                            value=covered - len(matched_blocks),
                            help_=FLEET_HELP[
                                "ctpu_fleet_prefix_blocks_total"],
                        )
                        self.registry.inc(
                            "ctpu_fleet_prefix_tokens_saved_total", None,
                            value=(covered - len(matched_blocks))
                            * self.block_size,
                            help_=FLEET_HELP[
                                "ctpu_fleet_prefix_tokens_saved_total"],
                        )
            if self.prefix is not None and shareable:
                self.prefix.note_lookup(
                    len(matched_blocks), shareable - len(matched_blocks)
                )
            if self.registry is not None and start:
                self.registry.inc(
                    "ctpu_lm_prefill_tokens_saved_total", None, value=start,
                    help_=LM_PREFIX_HELP["ctpu_lm_prefill_tokens_saved_total"],
                )
            # key=None: PRNGKey is itself a (jitted) device dispatch and
            # must not run under _cv — the first _prefill_step builds it
            self._job = _PrefillJob(
                handle, slot, blocks, table,
                chunk_plan(handle.prompt_len, self.buckets, start=start),
                None,
            )
            self._job.remote = job_remote
            self._scaler.note_ok(False, self._max_active_locked())

    def _job_cancelled_locked(self, job):
        """True when the stream this job serves went away: a normal
        admission's handle was cancelled, or a recompute-resume's
        swapped stream was."""
        if job.resume is not None:
            return job.resume.cancelled
        return job.handle.placed is _CANCELLED

    def _prefill_step(self):
        """Dispatch ONE chunk of the current prefill job (outside _cv);
        the final chunk activates the lane."""
        with self._cv:
            # re-read under the lock: a concurrent close() may have
            # aborted and cleared the job since the caller's check
            job = self._job
            if job is None:
                return
            if self._closed or self._job_cancelled_locked(job):
                self._abort_job_locked(job)
                self._job = None
                return
            # snapshot the remote-install plan under the lock: a close()
            # racing this step nulls job.blocks in _abort_job_locked, and
            # the consumed job.remote marks the blocks as real content
            # for the eventual give_back
            remote, job.remote = job.remote, None
            remote_blocks = (
                list(job.blocks[remote[0]:remote[1]])
                if remote is not None else None
            )
        handle = job.handle
        if remote is not None:
            # install the fleet-fetched KV content into the reservation's
            # fresh blocks (scheduler thread, outside _cv — the scatter
            # orders before this job's chunk dispatches below, so the
            # chunk's attention reads the peer-computed content).  The
            # host arrays cover chain blocks [rstart, covered); the
            # destination is blocks [lo, hi) of the reservation.
            lo, hi, host_k, host_v, rstart = remote
            idx = jnp.asarray(np.asarray(remote_blocks, np.int32))
            for layer in range(len(host_k)):
                self.kv.pools["k"][layer] = (
                    self.kv.pools["k"][layer].at[idx]
                    .set(jnp.asarray(host_k[layer][lo - rstart:hi - rstart]))
                )
                self.kv.pools["v"][layer] = (
                    self.kv.pools["v"][layer].at[idx]
                    .set(jnp.asarray(host_v[layer][lo - rstart:hi - rstart]))
                )
        if job.key is None:  # deferred out of _admit: dispatch-free lock
            job.key = jax.random.PRNGKey(handle.seed)
        start, width = job.plan[job.chunk_idx]
        chunk = pad_prompt(
            handle.prompt[:, start:start + width], width,
            pad_id=0,
        )
        t0 = time.monotonic()
        tok, pool_k, pool_v, job.key = self._prefill(
            self.params, jnp.asarray(chunk), self.kv.pools["k"],
            self.kv.pools["v"], jnp.asarray(job.table),
            jnp.int32(start), jnp.int32(handle.prompt_len), job.key,
            jnp.float32(handle.temperature), jnp.int32(handle.top_k),
        )
        self.kv.pools["k"] = pool_k
        self.kv.pools["v"] = pool_v
        job.chunk_idx += 1
        self._log_tick("prefill_chunk", t0, (job.slot,))
        if self.registry is not None:
            self.registry.inc(
                "ctpu_lm_prefill_chunks_total",
                help_="Prefill chunks dispatched between decode ticks",
            )
            # real (non-pad) prompt tokens this chunk computed — the
            # denominator side of the prefix-cache savings accounting
            self.registry.inc(
                "ctpu_lm_prefill_tokens_total", None,
                value=min(start + width, handle.prompt_len) - start,
                help_=LM_PREFIX_HELP["ctpu_lm_prefill_tokens_total"],
            )
        if job.chunk_idx < len(job.plan):
            return
        export = None
        with self._cv:
            self._job = None
            if self._closed or self._job_cancelled_locked(job):
                self._abort_job_locked(job)
                return
            lane = self._lanes[job.slot]
            resume = job.resume
            lane.gen += 1
            lane.active = True
            lane.table[:] = job.table
            lane.blocks, job.blocks = job.blocks, None
            if resume is None and self.fleet is not None:
                nfull = handle.prompt_len // self.block_size
                if nfull:
                    export = (
                        handle.prompt[0],
                        [int(b) for b in lane.blocks[:nfull]],
                        nfull,
                    )
            if resume is None:
                lane.queue = handle.queue
                lane.remaining = handle.max_tokens
                lane.produced = 0
                lane.length = handle.prompt_len
                lane.limit = handle.prompt_len + handle.max_tokens
                lane.tenant = handle.tenant
                lane.temperature = handle.temperature
                lane.top_k = handle.top_k
                lane.prompt = handle.prompt
                lane.tokens = []
                lane.handle = handle
                lane.spec = (
                    LaneSpec(self._spec, handle.prompt[0])
                    if self._spec is not None else None
                )
                handle.placed = (job.slot, lane.gen)
                if self.prefix is not None:
                    # the prompt's full blocks are fully written as of
                    # this chunk: publish them so a same-prefix burst
                    # shares from the first finished prefill
                    self.prefix.publish(
                        handle.prompt[0],
                        handle.prompt_len // self.block_size,
                        lane.blocks,
                    )
            else:
                # recompute-resume: the replayed prefill rebuilt the KV
                # for prompt + delivered tokens; streaming continues from
                # the SAVED counters, token and RNG carry — the chunk's
                # sampled token is discarded (that position's token was
                # already delivered before preemption)
                self._restore_lane_locked(lane, resume, job.slot)
            snapshot = ((job.slot, lane.gen),)
            self._lane_gauges_locked()
        if export is not None:
            self._export_prefix(export)
        if resume is not None:
            # install the saved next-tick input token + RNG carry; nothing
            # streams (everything up to `produced` was already delivered)
            self._tokens, self._keys = self._adopt(
                self._tokens, self._keys, jnp.int32(job.slot),
                jnp.int32(resume.token), jnp.asarray(resume.key),
            )
            return
        # install the first token + RNG carry into the lane arrays and
        # stream the token through the readback pipeline (single-lane
        # entry, exactly like a full tick's vector)
        self._tokens, self._keys = self._adopt(
            self._tokens, self._keys, jnp.int32(job.slot), tok, job.key
        )
        if hasattr(tok, "copy_to_host_async"):
            tok.copy_to_host_async()
        self._inflight.append((tok, snapshot))

    def _export_prefix(self, export):
        """Publish freshly prefilled full prompt blocks into the fleet
        tier's host store (scheduler thread, OUTSIDE _cv: the gather is
        a device read ordered after this job's chunk writes, and the
        store insert is host-side only).  One device->host copy per
        prefill — the price of making the prefix fleet-visible, paid
        only while a tier is attached."""
        row, blocks, nfull = export
        idx = jnp.asarray(np.asarray(blocks, np.int32))
        host_k = [np.asarray(p[idx]) for p in self.kv.pools["k"]]
        host_v = [np.asarray(p[idx]) for p in self.kv.pools["v"]]
        self.fleet.export_prefix(
            row, nfull, self.block_size, host_k, host_v
        )

    def drain(self):
        """Planned retire: migrate what can migrate, then close.

        Active lanes' prompt prefixes were already exported to the fleet
        tier at prefill completion, so a client replaying
        prompt + delivered tokens on a surviving replica resumes
        byte-exact with its prefill largely served from the tier.
        Parked (preempted) streams are the case with otherwise-stranded
        state: their host-swapped KV chains — prompt AND generated-token
        blocks — are exported here, and the swap store drops with the
        close (audited: no leaked blocks).  Returns the number of parked
        streams exported."""
        exports = []
        with self._cv:
            fleet = self.fleet if self.prefix is not None else None
            if fleet is not None:
                for entry in self._swapped:
                    if entry.cancelled or entry.host_k is None:
                        continue
                    nfull = entry.length // self.block_size
                    if not nfull:
                        continue
                    row = entry.prompt[0]
                    if entry.produced > 1:
                        # the written sequence is prompt + every delivered
                        # token except the last (which is the NEXT tick's
                        # input): exactly `length` tokens
                        row = np.concatenate([
                            row,
                            np.asarray(
                                entry.tokens[:entry.produced - 1], np.int32
                            ),
                        ])
                    exports.append(
                        (row, nfull, entry.host_k, entry.host_v)
                    )
        for row, nfull, host_k, host_v in exports:
            fleet.export_prefix(
                row, nfull, self.block_size,
                [a[:nfull] for a in host_k],
                [a[:nfull] for a in host_v],
            )
        if exports and self.registry is not None:
            self.registry.inc(
                "ctpu_fleet_sessions_migrated_total", None,
                value=len(exports),
                help_=FLEET_HELP["ctpu_fleet_sessions_migrated_total"],
            )
        self.close()
        return len(exports)

    def _tick_for(self, n):
        # memoized under _cv: decode_executables() iterates this dict
        # from the caller thread while the scheduler inserts — jax.jit
        # here only CONSTRUCTS the callable (tracing happens at the
        # dispatch site, outside the lock), so the critical section
        # stays cheap
        with self._cv:
            fn = self._tick_jits.get(n)
            if fn is None:
                fn = jax.jit(
                    functools.partial(
                        _decode_tick, cfg=self.cfg, n=n,
                        block_size=self.block_size,
                    ),
                    donate_argnums=self._donate,
                )
                self._tick_jits[n] = fn
        return fn

    def _decode_pass(self):
        """One batched decode tick over the active lanes (dispatch
        outside _cv).  Returns True if a tick ran."""
        with self._cv:
            if self._closed:
                return False
            n = self._scaler.n_lanes
            # a lane drops out of the tick batch once it has dispatched
            # its full token budget (readback may still be in flight) —
            # dispatch-ahead must never write past the lane's block
            # reservation
            active = [
                (i, self._lanes[i].gen)
                for i in range(n)
                if self._lanes[i].active
                and self._lanes[i].length < self._lanes[i].limit
            ]
            if not active:
                return False
            # lanes outside the batch (idle, or at-budget awaiting drain)
            # get a trash table + position 0: their scatter lands in the
            # trash block and their garbage token is never delivered
            included = {i for i, _ in active}
            trash_row = np.zeros((self._table_width,), np.int32)
            tables = np.stack([
                self._lanes[i].table if i in included else trash_row
                for i in range(n)
            ])
            lens = np.array(
                [self._lanes[i].length if i in included else 0
                 for i in range(n)], np.int32,
            )
            temps = np.array(
                [self._lanes[i].temperature for i in range(n)], np.float32
            )
            topks = np.array(
                [self._lanes[i].top_k for i in range(n)], np.int32
            )
            for i, _ in active:
                self._lanes[i].length += 1  # this tick writes position len
            self._lane_gauges_locked(active_count=len(active))
        t0 = time.monotonic()
        fn = self._tick_for(n)
        self._tokens, pool_k, pool_v, self._keys = fn(
            self.params, self._tokens, self.kv.pools["k"],
            self.kv.pools["v"], jnp.asarray(tables), jnp.asarray(lens),
            jnp.asarray(temps), jnp.asarray(topks), self._keys,
        )
        self.kv.pools["k"] = pool_k
        self.kv.pools["v"] = pool_v
        if hasattr(self._tokens, "copy_to_host_async"):
            self._tokens.copy_to_host_async()
        self._inflight.append((self._tokens, tuple(active)))
        self._log_tick("decode", t0, tuple(i for i, _ in active))
        return True

    def _verify_for(self, n, w):
        # memoized under _cv exactly like _tick_for: jit here only
        # CONSTRUCTS the callable, tracing happens at dispatch outside
        # the lock
        with self._cv:
            fn = self._verify_jits.get((n, w))
            if fn is None:
                fn = jax.jit(
                    functools.partial(
                        _verify_tick, cfg=self.cfg, n=n, width=w,
                        block_size=self.block_size,
                    ),
                    donate_argnums=self._donate,
                )
                self._verify_jits[(n, w)] = fn
        return fn

    def _spec_pass(self, ptick):
        """One speculative draft + verify pass over the active lanes;
        True when a verify tick ran, False to fall through to the plain
        decode tick.

        The fall-through IS the never-slower path: a lane whose adaptive
        k backed off to 0 skips drafting, and when NO lane drafts the
        pass returns before touching the readback pipeline — the engine
        then runs the exact plain-decode code (dispatch-ahead included),
        paying only this method's host-side enabled check.

        The verify tick is SYNCHRONOUS (no dispatch-ahead): how far a
        lane advances depends on its accepted count, which the host
        learns only at readback.  The in-flight pipeline is drained
        before drafting so each lane's host history is complete
        (``lane.tokens[-1]`` == the device-side next input token — the
        same consistency point ``_preempt_step`` establishes), and
        because verify never spans a pass boundary, a preemption, swap
        or cancel can never observe a half-applied verify: the
        swap/recompute byte-exactness argument is unchanged.
        """
        with self._cv:
            if self._closed:
                return False
            n = self._scaler.n_lanes
            want = False
            for i in range(n):
                lane = self._lanes[i]
                if (not lane.active or lane.spec is None
                        or lane.length >= lane.limit):
                    continue
                room = min(lane.limit - 1 - lane.length,
                           lane.remaining - lane.produced - 1)
                if lane.spec.k > 0 and room > 0:
                    want = True
                else:
                    lane.spec.note_plain()  # re-probe timer while k == 0
            if not want:
                return False
        while self._inflight:
            self._drain_one(ptick)
        cands = []
        with self._cv:
            if self._closed:
                return False
            for i in range(n):
                lane = self._lanes[i]
                if (not lane.active or lane.spec is None
                        or lane.length >= lane.limit or not lane.tokens):
                    continue
                room = min(lane.limit - 1 - lane.length,
                           lane.remaining - lane.produced - 1)
                if lane.spec.k <= 0 or room <= 0:
                    continue
                hist = np.concatenate([
                    lane.prompt[0], np.asarray(lane.tokens, np.int32),
                ])
                cands.append((i, lane.gen, lane.spec, hist, room))
        if not cands:
            return False
        # drafting is pure host work, outside the lock; its own phase +
        # tick-span so profview prices draft against verify and decode
        t_draft = time.monotonic()
        proposals = {}
        with ptick.phase("draft"):
            for i, gen, lane_spec, hist, room in cands:
                toks = lane_spec.draft(hist)[:room]
                if toks:
                    proposals[i] = (gen, toks)
        if not proposals:
            return False
        self._log_tick("draft", t_draft, tuple(sorted(proposals)))
        with self._cv:
            if self._closed:
                return False
            active = [
                (i, self._lanes[i].gen)
                for i in range(n)
                if self._lanes[i].active
                and self._lanes[i].length < self._lanes[i].limit
            ]
            if not active:
                return False
            included = {i for i, _ in active}
            # gen-checked: a lane cancelled while drafting drops its
            # proposal; other active lanes ride the tick as plain decode
            # (count 0 — they deliver exactly one token)
            drafts = {
                i: toks for i, (gen, toks) in proposals.items()
                if i in included and self._lanes[i].gen == gen
            }
            if not drafts:
                return False
            max_d = max(len(toks) for toks in drafts.values())
            w = bucket_for(max_d + 1, self._verify_widths)
            props = np.zeros((n, w - 1), np.int32)
            counts = np.zeros((n,), np.int32)
            for i, toks in drafts.items():
                d = min(len(toks), w - 1)
                props[i, :d] = toks[:d]
                counts[i] = d
            trash_row = np.zeros((self._table_width,), np.int32)
            tables = np.stack([
                self._lanes[i].table if i in included else trash_row
                for i in range(n)
            ])
            lens = np.array(
                [self._lanes[i].length if i in included else 0
                 for i in range(n)], np.int32,
            )
            temps = np.array(
                [self._lanes[i].temperature for i in range(n)], np.float32
            )
            topks = np.array(
                [self._lanes[i].top_k for i in range(n)], np.int32
            )
            self._lane_gauges_locked(active_count=len(active))
        t0 = time.monotonic()
        fn = self._verify_for(n, w)
        with ptick.phase("verify_dispatch"):
            out, self._tokens, pool_k, pool_v, self._keys = fn(
                self.params, self._tokens, self.kv.pools["k"],
                self.kv.pools["v"], jnp.asarray(tables),
                jnp.asarray(lens), jnp.asarray(temps),
                jnp.asarray(topks), self._keys, jnp.asarray(props),
                jnp.asarray(counts),
            )
            self.kv.pools["k"] = pool_k
            self.kv.pools["v"] = pool_v
        with ptick.phase("device_wait"):
            vals = np.asarray(out)  # [2, n]: accepted count, correction
        self._log_tick("verify", t0, tuple(i for i, _ in active))
        self._deliver_verified(ptick, active, vals, props, counts)
        return True

    def _deliver_verified(self, ptick, active, vals, props, counts):
        """Stream one verify tick's accepted drafts + correction token
        per lane and advance the per-lane length/budget/adaptive-k
        bookkeeping (under _cv; the tick already completed on device)."""
        delivered = 0
        proposed = accepted = 0
        with ptick.phase("deliver"), self._cv:
            for slot_idx, gen in active:
                lane = self._lanes[slot_idx]
                if not lane.active or lane.gen != gen:
                    continue  # cancelled since dispatch: stale tick
                d = int(counts[slot_idx])
                acc = min(int(vals[0, slot_idx]), d)
                toks = [int(t) for t in props[slot_idx, :acc]]
                toks.append(int(vals[1, slot_idx]))
                if lane.spec is not None:
                    if d:
                        lane.spec.note(d, acc)
                    else:
                        lane.spec.note_plain()
                proposed += d
                accepted += acc
                for token in toks:
                    lane.queue.put(token)
                    lane.produced += 1
                    lane.tokens.append(token)
                    # the tick wrote K/V for this token's position; the
                    # first garbage (rejected) position becomes the next
                    # tick's write position — the rewind is this pointer
                    lane.length += 1
                    delivered += 1
                    if self.registry is not None:
                        self.registry.inc(
                            "ctpu_lm_tokens_total",
                            help_="Tokens streamed by the LM engine",
                        )
                    if (lane.produced >= lane.remaining
                            or (self.eos_id is not None
                                and token == self.eos_id)):
                        self._retire_lane_locked(lane)
                        break
            self._spec_proposed += proposed
            self._spec_accepted += accepted
            if proposed and self.registry is not None:
                self.registry.inc(
                    "ctpu_lm_spec_proposed_tokens_total", None,
                    value=proposed,
                    help_=LM_SPEC_HELP[
                        "ctpu_lm_spec_proposed_tokens_total"],
                )
                if accepted:
                    self.registry.inc(
                        "ctpu_lm_spec_accepted_tokens_total", None,
                        value=accepted,
                        help_=LM_SPEC_HELP[
                            "ctpu_lm_spec_accepted_tokens_total"],
                    )
                if proposed - accepted:
                    self.registry.inc(
                        "ctpu_lm_spec_rejected_tokens_total", None,
                        value=proposed - accepted,
                        help_=LM_SPEC_HELP[
                            "ctpu_lm_spec_rejected_tokens_total"],
                    )
                self.registry.set(
                    "ctpu_lm_spec_acceptance_rate", None,
                    round(
                        self._spec_accepted
                        / max(self._spec_proposed, 1), 4,
                    ),
                    help_=LM_SPEC_HELP["ctpu_lm_spec_acceptance_rate"],
                )
        if delivered:
            ptick.compute("lm", delivered, self._flops_per_token)

    def _log_tick(self, kind, t0, slots):
        t1 = time.monotonic()
        with self._cv:
            self._tick_log.append({
                "kind": kind, "t0": t0, "t1": t1, "lanes": slots,
                "n_lanes": self._scaler.n_lanes,
            })
        tracer = self.tracer
        if tracer is not None:
            tracer.tick_span(kind, t0, t1)

    def _drain_one(self, ptick=NULL_TICK):
        tokens_dev, snapshot = self._inflight.popleft()
        with ptick.phase("device_wait"):
            # the host-side materialization is where async dispatch pays:
            # this np.asarray blocks until the tick's device work lands
            vals = np.asarray(tokens_dev).reshape(-1)
        delivered = 0
        with ptick.phase("deliver"), self._cv:
            for slot_idx, gen in snapshot:
                lane = self._lanes[slot_idx]
                if not lane.active or lane.gen != gen:
                    continue  # cancelled/finished lane: stale tick token
                # full ticks carry one token PER LANE (index by slot);
                # single-lane prefill entries carry exactly one value
                token = (
                    int(vals[slot_idx]) if vals.size > 1 else int(vals[0])
                )
                lane.queue.put(token)
                lane.produced += 1
                lane.tokens.append(token)  # recompute-replay history
                delivered += 1
                if self.registry is not None:
                    self.registry.inc(
                        "ctpu_lm_tokens_total",
                        help_="Tokens streamed by the LM engine",
                    )
                done = (
                    lane.produced >= lane.remaining
                    or (self.eos_id is not None and token == self.eos_id)
                )
                if done:
                    self._retire_lane_locked(lane)
        if delivered:
            ptick.compute("lm", delivered, self._flops_per_token)

    # -- preemption / swap -------------------------------------------------

    def _preempt_step(self):
        """Swap the victim _admit chose out to the host store (or drop
        its KV for recompute when the store is full).  Scheduler thread;
        every device copy runs OUTSIDE _cv."""
        # deliver every dispatched token first so the swap record's
        # counters (produced/length) and the lane arrays' token/RNG carry
        # describe one consistent preemption point
        while self._inflight:
            self._drain_one()
        with self._cv:
            decision, self._preempt = self._preempt, None
            if decision is None or self._closed:
                return
            slot, gen = decision
            lane = self._lanes[slot]
            if not lane.active or lane.gen != gen:
                return  # completed or cancelled since the decision
            written_blocks = -(-lane.length // self.block_size)
            blocks = [int(b) for b in lane.table[:written_blocks]]
            n_blocks = len(lane.blocks)
            use_swap = (
                self._swapped_blocks + written_blocks
                <= self.swap_block_limit
            )
        # device -> host gather outside the lock: scheduler-thread
        # dispatch order guarantees every write to these blocks was
        # issued before this read, and nobody re-allocates them until
        # the release below
        host_k = host_v = None
        if use_swap:
            idx = jnp.asarray(np.asarray(blocks, np.int32))
            host_k = [np.asarray(p[idx]) for p in self.kv.pools["k"]]
            host_v = [np.asarray(p[idx]) for p in self.kv.pools["v"]]
        token = int(np.asarray(self._tokens)[slot])
        key = np.asarray(self._keys)[slot].copy()
        with self._cv:
            lane = self._lanes[slot]
            if self._closed or not lane.active or lane.gen != gen:
                return  # raced with cancel/close: drop the copies
            entry = _Swapped(
                lane, n_blocks, written_blocks, token, key, host_k, host_v
            )
            self._swapped.append(entry)
            if entry.handle is not None:
                entry.handle.placed = entry
            if use_swap:
                self._swapped_blocks += written_blocks
            self._preemptions += 1
            if self.registry is not None:
                self.registry.inc(
                    "ctpu_lm_preemptions_total", None,
                    help_=LM_PREFIX_HELP["ctpu_lm_preemptions_total"],
                )
            if self.flight is not None:
                self.flight.note(
                    "lm_preemption", slot=slot, tenant=lane.tenant,
                    swapped=bool(use_swap), blocks=written_blocks,
                )
            self._swap_gauge_locked()
            # pause, don't end: the stream's queue stays open
            self._retire_lane_locked(lane, close_queue=False)

    def _resume_step(self):
        """Swap one parked stream back in when a free lane + blocks
        exist and no queued request outranks it (otherwise the blocks a
        preemption just freed would thrash straight back to the stream
        it preempted)."""
        plan = None
        with self._cv:
            if (self._closed or not self._swapped or self._job is not None
                    or self._preempt is not None):
                return
            n_lanes = self._scaler.n_lanes
            slot = next(
                (i for i in range(n_lanes) if not self._lanes[i].active),
                None,
            )
            if slot is None:
                return
            queued_pri = None
            for tenant, dq in self._pending.items():
                if dq:
                    pri = self._priority_of(tenant)
                    queued_pri = (
                        pri if queued_pri is None else max(queued_pri, pri)
                    )
            order = sorted(
                range(len(self._swapped)),
                key=lambda i: (
                    -self._priority_of(self._swapped[i].tenant), i,
                ),
            )
            for i in order:
                entry = self._swapped[i]
                if entry.cancelled:
                    continue  # cancel() removes eagerly; belt and braces
                if (queued_pri is not None
                        and self._priority_of(entry.tenant) < queued_pri):
                    continue
                if entry.host_k is not None:
                    row = entry.prompt[0]
                    cap = min(entry.prompt_len // self.block_size,
                              entry.written_blocks)
                else:
                    # recompute: the replay chain is prompt + delivered
                    # tokens, so cached generated-token blocks match too
                    row = np.concatenate([
                        entry.prompt[0],
                        np.asarray(entry.tokens[:entry.produced - 1],
                                   np.int32),
                    ])
                    cap = (entry.length - 1) // self.block_size
                matched_blocks, matched_nodes = [], []
                if self.prefix is not None and cap:
                    matched_blocks, matched_nodes = self.prefix.match(
                        row, cap
                    )
                    self.prefix.adopt(matched_nodes)
                fresh = self._reserve_locked(entry.n_blocks, matched_blocks)
                if fresh is None:
                    if matched_blocks:
                        self.kv.release(matched_blocks)
                    continue
                self._swapped.pop(i)
                blocks = matched_blocks + fresh
                table = np.full(
                    (self._table_width,), KvBlockPool.TRASH, np.int32
                )
                table[:len(blocks)] = blocks
                if entry.host_k is None:
                    pseudo = row[None, :].astype(np.int32)
                    handle = _Handle(
                        pseudo, entry.limit - entry.length, entry.queue,
                        entry.tenant, entry.temperature, entry.top_k, 0,
                    )
                    job = _PrefillJob(
                        handle, slot, blocks, table,
                        chunk_plan(
                            entry.length, self.buckets,
                            start=len(matched_blocks) * self.block_size,
                        ),
                        None,
                    )
                    job.resume = entry
                    self._job = job  # _prefill_step replays from here
                    return
                plan = (entry, slot, blocks, len(matched_blocks), table,
                        entry.host_k, entry.host_v)
                break
        if plan is None:
            return
        entry, slot, blocks, n_matched, table, host_k, host_v = plan
        # restore the written, non-adopted blocks from the host store —
        # un-jitted .at[].set (one pool copy per layer): resume is a rare
        # pressure event, correctness beats the copy here
        dst = np.asarray(blocks[n_matched:entry.written_blocks], np.int32)
        if dst.size:
            idx = jnp.asarray(dst)
            sel = slice(n_matched, entry.written_blocks)
            for layer in range(len(host_k)):
                self.kv.pools["k"][layer] = (
                    self.kv.pools["k"][layer].at[idx]
                    .set(jnp.asarray(host_k[layer][sel]))
                )
                self.kv.pools["v"][layer] = (
                    self.kv.pools["v"][layer].at[idx]
                    .set(jnp.asarray(host_v[layer][sel]))
                )
        with self._cv:
            if self._closed or entry.cancelled:
                # the stream died while restoring: unwind the reservation.
                # host_k is the plan-local reference — cancel may have
                # nulled the entry's.
                if self._closed:
                    # _release_all_locked already zeroed _swapped_blocks
                    # (and cleared the cache), so no gauge decrement here.
                    # The entry was popped from _swapped BEFORE close ran,
                    # so close's sweep missed its queue: close it here or
                    # the consumer blocks on q.get() forever.
                    if not entry.cancelled:
                        entry.cancelled = True
                        entry.queue.put(_CLOSE)
                    self.kv.release(blocks)
                else:
                    self._release_blocks_locked(
                        entry.prompt, entry.length, blocks
                    )
                    self._swapped_blocks -= entry.written_blocks
                    self._swap_gauge_locked()
                return
            lane = self._lanes[slot]
            lane.gen += 1
            lane.active = True
            lane.table[:] = table
            lane.blocks = blocks
            self._restore_lane_locked(lane, entry, slot)
            self._swapped_blocks -= entry.written_blocks
            self._swap_gauge_locked()
            self._lane_gauges_locked()
        # install the saved next-tick input token + RNG carry (scheduler
        # thread: the next decode pass dispatches strictly after this)
        self._tokens, self._keys = self._adopt(
            self._tokens, self._keys, jnp.int32(slot),
            jnp.int32(entry.token), jnp.asarray(entry.key),
        )

    def _loop(self):
        try:
            self._loop_inner()
        except Exception as exc:
            # a dying scheduler must never strand consumers on q.get()
            with self._cv:
                self._release_all_locked()
                self._closed = True
            # an engine wedge is the flagship flight-recorder anomaly:
            # capture the ring (recent ticks, spans, preemptions) NOW —
            # the postmortem must not depend on tracing having been on
            flight = self.flight
            if flight is not None:
                flight.note("lm_engine_wedge", error=repr(exc))
                flight.dump("lm_engine_wedge")
            raise

    def _loop_inner(self):
        while True:
            # every pass is one profiler tick; finish-in-finally is the
            # bracket shape the SPAN-LEAK lint demands, so a pass that
            # dies still commits the phases it measured before wedging
            tick = self.prof.start_tick("sched")
            self._ptick = tick
            try:
                alive = self._loop_pass(tick)
            finally:
                self._ptick = NULL_TICK
                self.prof.finish(tick)
            if not alive:
                break
        # shutdown: drop the in-flight tail (queues already closed)
        self._inflight.clear()

    def _loop_pass(self, ptick):
        """One scheduler pass (the former _loop_inner body); returns
        False when the engine closed and the loop must stop."""
        if self._preempt is not None:
            with ptick.phase("preempt"):
                self._preempt_step()  # device copies outside _cv
        if self._swapped:
            with ptick.phase("resume"):
                self._resume_step()
        with ptick.phase("schedule"):
            self._admit()  # takes/releases _cv itself; no dispatch inside
        worked = False
        if self._job is not None:
            with ptick.phase("prefill_dispatch"):
                self._prefill_step()  # ONE chunk, outside _cv
            ptick.relabel("prefill")
            worked = True
        verified = False
        if self._spec is not None:
            # _spec_pass brackets its own phases (draft / verify_dispatch
            # / device_wait / deliver); False falls through to the plain
            # decode tick — the never-slower path
            verified = self._spec_pass(ptick)
        ticked = verified
        if not ticked:
            with ptick.phase("decode_dispatch"):
                ticked = self._decode_pass()  # ONE decode tick, outside _cv
        if ticked:
            ptick.relabel("verify" if verified else "decode")
        worked = worked or ticked
        with self._cv:
            if self._closed:
                return False
        while len(self._inflight) > (self.depth if ticked else 0):
            self._drain_one(ptick)
        if not worked and not self._inflight:
            with self._cv:
                if self._closed:
                    return False
                # swapped streams deliberately DON'T block the wait:
                # an unresumable one (blocks pinned) retries on the
                # 50ms tick instead of busy-spinning the loop
                if (not self._queued_locked()
                        and self._job is None
                        and not any(l.active for l in self._lanes)):
                    ptick.relabel("idle")
                    with ptick.phase("idle"):
                        self._cv.wait_for(
                            lambda: (self._closed
                                     or self._job is not None
                                     or self._queued_locked()
                                     or any(l.active
                                            for l in self._lanes)),
                            timeout=0.05,
                        )
        return True
