"""Admission policy pieces: prompt-length bucketing and lane autoscaling.

Bucketing exists because ``jax.jit`` keys executables on shape: a prefill
invoked at every distinct prompt length compiles a fresh XLA program per
length (seconds each on a real chip), unbounded by anything but client
behavior.  Padding prompts to a small geometric set of widths makes the
executable count provably ``<= len(buckets)``; prompts longer than the
largest bucket run as a sequence of largest-bucket-wide chunks, so the
chunk width set IS the compiled-shape set.
"""

import numpy as np


def geometric_buckets(min_bucket, max_bucket, factor=2):
    """Geometric prefill-width set: ``min_bucket * factor^i`` capped at
    ``max_bucket`` (always included).  These are the ONLY shapes the
    prefill executable ever compiles for."""
    if min_bucket <= 0 or max_bucket <= 0:
        raise ValueError("buckets must be positive")
    min_bucket = min(min_bucket, max_bucket)
    buckets = []
    width = int(min_bucket)
    while width < max_bucket:
        buckets.append(width)
        width *= int(factor)
    buckets.append(int(max_bucket))
    return tuple(buckets)


def bucket_for(n, buckets):
    """Smallest bucket >= n, or the largest bucket (the chunk width) for
    prompts that span multiple chunks."""
    for width in buckets:
        if n <= width:
            return width
    return buckets[-1]


def pad_prompt(prompt, width, pad_id=0):
    """Right-pad a ``[1, T]`` int32 prompt to ``[1, width]``.  Padded
    positions are never written to the KV pool (the chunk kernel's write
    mask) and never attended (the causal/length mask), so the pad id is
    semantically inert — it only fixes the dispatch shape."""
    prompt = np.asarray(prompt, np.int32).reshape(1, -1)
    t = prompt.shape[1]
    if t > width:
        raise ValueError(f"prompt of {t} tokens exceeds pad width {width}")
    if t == width:
        return prompt
    out = np.full((1, width), int(pad_id), np.int32)
    out[0, :t] = prompt[0]
    return out


def chunk_plan(prompt_len, buckets, start=0):
    """The per-chunk (start, width) dispatch plan for one prompt.

    ``start`` > 0 skips positions already in the KV cache (prefix-cache
    adoption: the matched blocks' tokens need no recompute, so the plan
    covers only ``[start, prompt_len)``).  Remainders <= the largest
    bucket run as ONE chunk at ``bucket_for`` width; longer remainders
    run max-bucket-wide chunks back to back (the final chunk pads).
    Every width in the plan is a member of ``buckets`` — that is the
    bounded-compile invariant tests assert: adoption changes WHERE
    prefill starts, never which shapes compile.
    """
    start = int(start)
    if not 0 <= start < prompt_len:
        raise ValueError(f"chunk start {start} outside [0, {prompt_len})")
    chunk = buckets[-1]
    remaining = prompt_len - start
    if remaining <= chunk:
        return [(start, bucket_for(remaining, buckets))]
    return [(s, chunk) for s in range(start, prompt_len, chunk)]


def verify_widths(max_k, min_width=2):
    """The speculative verify tick's fixed window widths: geometric from
    ``min_width`` up to ``max_k + 1`` (k draft tokens + the pending input
    token).  Same bounded-compile discipline as prefill bucketing — a
    verify dispatch pads its draft count up to the next width, so the
    verify executable set is provably
    ``<= len(verify_widths(k)) * len(lane_counts)``."""
    if max_k < 1:
        raise ValueError("speculative k must be >= 1")
    return geometric_buckets(min(min_width, max_k + 1), max_k + 1)


class LaneAutoscaler:
    """Step the decode lane count through a small precompiled set.

    Scale-up: ``up_after`` consecutive scheduler passes with admissible
    pending work but no free lane.  Scale-down: ``down_after``
    consecutive passes where nothing is pending and every active lane
    fits in the next-smaller count (admission always fills the
    lowest-index free lane, so "fits" is just ``max active index``).
    Hysteresis on both sides keeps one bursty tenant from thrashing the
    executable set.
    """

    def __init__(self, lane_counts, up_after=3, down_after=50):
        counts = sorted(set(int(c) for c in lane_counts))
        if not counts or counts[0] < 1:
            raise ValueError("lane_counts must be positive")
        self.counts = tuple(counts)
        self.up_after = int(up_after)
        self.down_after = int(down_after)
        self._idx = 0
        self._starved = 0
        self._idle = 0

    @property
    def n_lanes(self):
        return self.counts[self._idx]

    def note_starved(self):
        """Pending work found no free lane this pass; maybe scale up."""
        self._idle = 0
        self._starved += 1
        if self._starved >= self.up_after and self._idx + 1 < len(self.counts):
            self._idx += 1
            self._starved = 0
            return True
        return False

    def note_ok(self, pending, max_active_index):
        """One pass with a free lane (or nothing pending); maybe scale
        down.  ``max_active_index`` is -1 when no lane is active."""
        self._starved = 0
        if self._idx == 0:
            self._idle = 0
            return False
        lower = self.counts[self._idx - 1]
        if pending or max_active_index >= lower:
            self._idle = 0
            return False
        self._idle += 1
        if self._idle >= self.down_after:
            self._idx -= 1
            self._idle = 0
            return True
        return False
