"""Radix/trie prefix cache over token-block hashes for the paged KV pool.

Production LM traffic reuses prompt prefixes heavily — shared system
prompts, few-shot templates, the whole history of a multi-turn chat —
and prefill is the compute-bound phase, so cross-request prefix reuse is
where most of the prefill FLOPs come back from.  The paged KV layout
(serve/lm/kv.py) was built for exactly this: blocks are position-fixed
(K is RoPE'd with its absolute position), so a full block of prompt
tokens at logical block index ``b`` is bit-identical for every request
whose first ``(b + 1) * block_size`` tokens match.  This module caches
those blocks and lets admission adopt them **by reference**.

Structure: a radix trie keyed by the token-chain — each node is one FULL
block of prompt tokens, its children keyed by the next block's token
tuple.  Matching walks the new prompt block-by-block from the root;
exact tuple keys (not just hashes) mean a match is a guarantee, never a
collision gamble.  Each cached node holds one pool reference on its
block (``KvBlockPool.retain``/``release`` semantics), so an active
request and the cache can share a block without either freeing it under
the other.

Lifecycle:

- **admission** (`match` + `adopt`): the engine walks the prompt's full
  blocks; every matched block is retained for the lane and chunked
  prefill starts at the first miss — an 80%-shared prompt runs ~20% of
  its prefill compute;
- **retirement** (`give_back`): a completed/cancelled request's fully
  written full prompt blocks are INSERTED into the trie (the lane's
  reference transfers to the cache) instead of freed; everything else
  (partial tail block, generated-token blocks) is released;
- **pressure** (`evict`): the cache holds blocks only as long as the
  pool is not starved — when an allocation falls short, the engine
  evicts least-recently-used leaf nodes whose block nobody else
  references until the reservation fits.  LRU over leaves keeps every
  cached chain contiguous from the root (a hole in the middle of a
  chain would make its suffix unreachable anyway).

Thread-safety: externally synchronized — every method is called with
the engine's ``_cv`` held (admission, retirement and eviction are all
scheduler-side bookkeeping).  All work here is host-side dict/list
manipulation; nothing blocks and nothing dispatches to the device, so
holding the condition lock is safe (the BLOCK-UNDER-LOCK gate agrees).
"""

import heapq

from client_tpu.serve.metrics import LM_PREFIX_HELP


class _Node:
    """One cached full block of prompt tokens."""

    __slots__ = ("tokens", "block", "parent", "children", "stamp")

    def __init__(self, tokens, block, parent):
        self.tokens = tokens      # tuple of this block's token ids
        self.block = block        # physical pool block index
        self.parent = parent      # _Node or the root sentinel None
        self.children = {}        # token tuple -> _Node
        self.stamp = 0            # LRU clock value of the last touch


class PrefixCache:
    """Trie of cached prompt-prefix KV blocks over a ``KvBlockPool``.

    ``min_prefix_blocks`` is the per-model hint knob: prefixes shorter
    than this many full blocks are not worth the table bookkeeping and
    are reported as a miss (0 = adopt any match).
    """

    def __init__(self, pool, registry=None, min_prefix_blocks=1):
        self.pool = pool
        self.block_size = pool.block_size
        self.registry = registry
        self.min_prefix_blocks = max(int(min_prefix_blocks), 0)
        self._children = {}  # root level: token tuple -> _Node
        self._nodes = 0
        self._clock = 0
        self.hits = 0        # blocks adopted
        self.misses = 0      # shareable full blocks with no cached match
        self.evictions = 0   # blocks evicted under pool pressure
        self.inserted = 0    # blocks handed over by retiring requests

    # -- internals ---------------------------------------------------------

    def _tick(self):
        self._clock += 1
        return self._clock

    def _blocks_of(self, prompt_row, limit):
        """The prompt's leading full-block token tuples, at most *limit*."""
        bs = self.block_size
        out = []
        for i in range(limit):
            out.append(tuple(int(t) for t in prompt_row[i * bs:(i + 1) * bs]))
        return out

    def _gauge(self):
        if self.registry is not None:
            self.registry.set(
                "ctpu_lm_prefix_cached_blocks", None, self._nodes,
                help_=LM_PREFIX_HELP["ctpu_lm_prefix_cached_blocks"],
            )

    def _count(self, name, value=1):
        if self.registry is not None and value:
            self.registry.inc(name, None, value=value,
                              help_=LM_PREFIX_HELP[name])

    # -- admission ---------------------------------------------------------

    def match(self, prompt_row, max_blocks):
        """Longest cached chain for this prompt, as ``(blocks, nodes)``.

        ``max_blocks`` caps the walk (the engine passes
        ``(prompt_len - 1) // block_size`` so at least one prompt token
        is always left to prefill — the final position's logits seed the
        first generated token).  Pure lookup: no refcounts move until
        :meth:`adopt`, so a failed admission has nothing to unwind.
        """
        nodes = []
        children = self._children
        for key in self._blocks_of(prompt_row, max_blocks):
            node = children.get(key)
            if node is None:
                break
            nodes.append(node)
            children = node.children
        if len(nodes) < self.min_prefix_blocks:
            nodes = []
        return [n.block for n in nodes], nodes

    def adopt(self, nodes):
        """Take one reference per matched block for the admitting lane
        and refresh the chain's LRU stamps."""
        if not nodes:
            return
        stamp = self._tick()
        for node in nodes:
            node.stamp = stamp
        self.pool.retain([n.block for n in nodes])

    def note_lookup(self, hits, misses):
        """Count one COMMITTED admission's lookup outcome (called after
        the reservation succeeds — a backpressured admission re-matches
        on retry and must not double-count)."""
        self.hits += hits
        self.misses += misses
        self._count("ctpu_lm_prefix_hits_total", hits)
        self._count("ctpu_lm_prefix_misses_total", misses)

    def publish(self, prompt_row, cacheable_blocks, blocks):
        """Make a live lane's full prompt blocks matchable NOW — called
        at prefill completion, so a burst of same-prefix admissions
        shares from the FIRST finished prefill instead of waiting for a
        whole stream to retire.  New nodes take their own pool reference
        (the lane keeps its); chains that already exist are only
        LRU-touched."""
        cacheable_blocks = min(int(cacheable_blocks), len(blocks))
        stamp = self._tick()
        children = self._children
        parent = None
        fresh = []
        for i, key in enumerate(self._blocks_of(prompt_row,
                                                cacheable_blocks)):
            node = children.get(key)
            if node is None:
                node = _Node(key, blocks[i], parent)
                children[key] = node
                self._nodes += 1
                self.inserted += 1
                fresh.append(blocks[i])
            node.stamp = stamp
            parent = node
            children = node.children
        if fresh:
            self.pool.retain(fresh)
            self._gauge()

    # -- retirement --------------------------------------------------------

    def give_back(self, prompt_row, cacheable_blocks, blocks):
        """Return a retiring request's reservation.

        ``blocks`` is the lane's ordered physical block list (adopted
        prefix + fresh); the first ``cacheable_blocks`` entries cover
        fully written FULL blocks of prompt tokens and are offered to
        the trie — a new node takes over the lane's reference, while a
        block whose chain node already exists (it was adopted, or an
        identical prompt retired first) is simply released.  Every
        remaining block (partial prompt tail, generation budget) is
        released outright.  Exactly one reference leaves the lane for
        every block either way: the refcount ledger stays balanced.
        """
        cacheable_blocks = min(int(cacheable_blocks), len(blocks))
        stamp = self._tick()
        to_release = list(blocks[cacheable_blocks:])
        children = self._children
        parent = None
        for i, key in enumerate(self._blocks_of(prompt_row,
                                                cacheable_blocks)):
            block = blocks[i]
            node = children.get(key)
            if node is None:
                # new chain entry: the lane's reference TRANSFERS to the
                # cache (no release — the cache now keeps the block warm)
                node = _Node(key, block, parent)
                children[key] = node
                self._nodes += 1
                self.inserted += 1
            else:
                # chain node already holds this content (the lane adopted
                # it, or an identical prompt retired first): the cache has
                # its own reference, so the lane's reference drops —
                # whether ``block`` is the shared block or a duplicate
                # computation of the same tokens
                to_release.append(block)
            node.stamp = stamp
            parent = node
            children = node.children
        self._gauge()
        self.pool.release(to_release)

    # -- pressure ----------------------------------------------------------

    def evict(self, n_blocks):
        """Free at least ``n_blocks`` pool blocks by dropping LRU leaf
        nodes nobody else references.  Returns the number actually
        freed (0 when every cached block is pinned by an active lane).

        Leaves-first keeps chains rooted: evicting an interior node
        would orphan its suffix, which no future walk could reach.  One
        DFS collects the evictable leaves into an LRU heap; a parent
        whose last child is evicted is promoted onto it — O(N log N)
        per call instead of one full-trie rescan per freed block (the
        caller holds the engine's _cv, so a rescan per block would
        stall every decode tick while backpressured).
        """
        n_blocks = int(n_blocks)
        if n_blocks <= 0 or not self._children:
            return 0
        heap = []
        seq = 0  # tie-break: nodes never compare
        stack = list(self._children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif self.pool.ref_count(node.block) == 1:
                heapq.heappush(heap, (node.stamp, seq, node))
                seq += 1
        released = []
        while len(released) < n_blocks and heap:
            _, _, victim = heapq.heappop(heap)
            siblings = (
                victim.parent.children if victim.parent is not None
                else self._children
            )
            del siblings[victim.tokens]
            self._nodes -= 1
            self.evictions += 1
            self._count("ctpu_lm_prefix_evictions_total")
            released.append(victim.block)
            parent = victim.parent
            if (parent is not None and not parent.children
                    and self.pool.ref_count(parent.block) == 1):
                heapq.heappush(heap, (parent.stamp, seq, parent))
                seq += 1
        self.pool.release(released)
        if released:
            self._gauge()
        return len(released)

    def clear(self):
        """Drop every cached block (engine shutdown): the pool must end
        fully free so close() leaves no leaked references behind."""
        blocks = []
        stack = list(self._children.values())
        while stack:
            node = stack.pop()
            blocks.append(node.block)
            stack.extend(node.children.values())
        self._children = {}
        self._nodes = 0
        self._gauge()
        self.pool.release(blocks)

    # -- introspection -----------------------------------------------------

    @property
    def cached_blocks(self):
        return self._nodes

    def stats(self):
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "inserted": self.inserted,
            "cached_blocks": self._nodes,
        }
