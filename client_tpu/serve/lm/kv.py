"""Paged KV cache: a refcounted block-table pool shared by every decode
lane (and, since the prefix cache, by every REQUEST that shares a prompt
prefix).

The fixed-lane prototype allocated per layer ``[lanes, max_seq, kv, hd]``
— every lane pinned max-seq-len rows of HBM whether it held a 5-token
request, a 500-token one, or nothing.  The pool here is per layer
``[n_blocks, block_size, kv, hd]`` with a host-side free list: a request
reserves exactly ``ceil((prompt_len + max_tokens) / block_size)`` blocks
at admission and frees them at completion/cancel, so HBM capacity is a
function of *aggregate live tokens*, not ``lanes * max_seq``.

Blocks carry a REFERENCE COUNT: ``alloc`` hands out blocks at refcount 1,
``retain`` adds a reference (a second request adopting a shared prompt-
prefix block, or the prefix cache keeping a retired request's blocks
warm), and ``release`` decrements — a block returns to the free list only
when its last reference drops.  That is what makes block-granular KV
sharing safe: an 80%-shared prompt adopts its prefix blocks by reference
instead of recomputing them, and nobody can free a block out from under
another holder.

Static shapes throughout (TPU-first): the device arrays never change
shape; splice/free are index bookkeeping on the host plus
scatter/gather through per-lane block tables inside the jitted programs.
Block 0 is reserved as the *trash block*: idle lanes and write-masked
pad positions scatter there, so the jitted tick needs no per-lane
branch.  Nothing ever reads it (the length mask excludes every position
a table maps to trash).
"""

import threading

import jax.numpy as jnp

_KV_HELP = {
    "ctpu_lm_kv_blocks_used": "Paged-KV blocks currently referenced",
    "ctpu_lm_kv_blocks_free": "Paged-KV blocks free in the pool",
}


class KvBlockPool:
    """Device block pool + host free-list/refcount accounting.

    ``n_blocks`` counts usable blocks; one extra trash block (index 0) is
    allocated on top, so the device arrays hold ``n_blocks + 1`` blocks.
    """

    TRASH = 0

    def __init__(self, cfg, n_blocks, block_size, registry=None):
        if block_size <= 0 or n_blocks <= 0:
            raise ValueError("block_size and n_blocks must be positive")
        self.cfg = cfg
        self.block_size = int(block_size)
        self.n_blocks = int(n_blocks)
        self.registry = registry
        shape = (self.n_blocks + 1, self.block_size,
                 cfg.n_kv_heads, cfg.head_dim)
        self.pools = {
            "k": [jnp.zeros(shape, cfg.jdtype) for _ in range(cfg.n_layers)],
            "v": [jnp.zeros(shape, cfg.jdtype) for _ in range(cfg.n_layers)],
        }
        self._lock = threading.Lock()
        self._free = list(range(1, self.n_blocks + 1))
        self._refs = {}  # block -> live reference count (absent = free)

    def blocks_for(self, n_tokens):
        """Blocks a sequence of ``n_tokens`` total (prompt + generation
        budget) reserves."""
        return -(-int(n_tokens) // self.block_size)

    def alloc(self, n):
        """Reserve ``n`` blocks at refcount 1; returns the block index
        list or None when the pool cannot satisfy the reservation
        (admission backpressure — the caller evicts cache blocks,
        preempts a lane, or retries once completions free blocks)."""
        n = int(n)
        with self._lock:
            if n > len(self._free):
                return None
            taken = self._free[:n]
            del self._free[:n]
            for block in taken:
                self._refs[block] = 1
            self._gauges_locked()
            return taken

    def retain(self, blocks):
        """Add one reference to each block (prefix-cache adoption; the
        block must already be live — retaining a freed block is a bug)."""
        with self._lock:
            for block in blocks:
                self._refs[block] += 1

    def release(self, blocks):
        """Drop one reference from each block; blocks whose last
        reference drops return to the free list.  Every ``alloc``/
        ``retain`` must be paired with exactly one release — the
        REFCOUNT-PAIR lint rule guards the shape (a leaked reference
        bricks the pool: the block is never free and never readable)."""
        if not blocks:
            return
        with self._lock:
            for block in blocks:
                left = self._refs[block] - 1
                if left > 0:
                    self._refs[block] = left
                else:
                    del self._refs[block]
                    self._free.append(block)
            self._gauges_locked()

    def ref_count(self, block):
        """Live reference count of one block (0 = free)."""
        with self._lock:
            return self._refs.get(block, 0)

    def ref_counts(self):
        """{block: refcount} snapshot of every live block (leak audits)."""
        with self._lock:
            return dict(self._refs)

    @property
    def free_blocks(self):
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self):
        with self._lock:
            return self.n_blocks - len(self._free)

    def _gauges_locked(self):
        if self.registry is None:
            return
        free = len(self._free)
        self.registry.set("ctpu_lm_kv_blocks_used", None,
                          self.n_blocks - free,
                          help_=_KV_HELP["ctpu_lm_kv_blocks_used"])
        self.registry.set("ctpu_lm_kv_blocks_free", None, free,
                          help_=_KV_HELP["ctpu_lm_kv_blocks_free"])

    def set_registry(self, registry):
        """Late-bind a metrics registry (the engine learns its server's
        registry at add_model time) and publish the current gauges."""
        with self._lock:
            self.registry = registry
            self._gauges_locked()
