"""Paged KV cache: a block-table pool shared by every decode lane.

The fixed-lane prototype allocated per layer ``[lanes, max_seq, kv, hd]``
— every lane pinned max-seq-len rows of HBM whether it held a 5-token
request, a 500-token one, or nothing.  The pool here is per layer
``[n_blocks, block_size, kv, hd]`` with a host-side free list: a request
reserves exactly ``ceil((prompt_len + max_tokens) / block_size)`` blocks
at admission and frees them at completion/cancel, so HBM capacity is a
function of *aggregate live tokens*, not ``lanes * max_seq``.

Static shapes throughout (TPU-first): the device arrays never change
shape; splice/free are index bookkeeping on the host plus
scatter/gather through per-lane block tables inside the jitted programs.
Block 0 is reserved as the *trash block*: idle lanes and write-masked
pad positions scatter there, so the jitted tick needs no per-lane
branch.  Nothing ever reads it (the length mask excludes every position
a table maps to trash).
"""

import threading

import jax.numpy as jnp

_KV_HELP = {
    "ctpu_lm_kv_blocks_used": "Paged-KV blocks currently reserved",
    "ctpu_lm_kv_blocks_free": "Paged-KV blocks free in the pool",
}


class KvBlockPool:
    """Device block pool + host free-list accounting.

    ``n_blocks`` counts usable blocks; one extra trash block (index 0) is
    allocated on top, so the device arrays hold ``n_blocks + 1`` blocks.
    """

    TRASH = 0

    def __init__(self, cfg, n_blocks, block_size, registry=None):
        if block_size <= 0 or n_blocks <= 0:
            raise ValueError("block_size and n_blocks must be positive")
        self.cfg = cfg
        self.block_size = int(block_size)
        self.n_blocks = int(n_blocks)
        self.registry = registry
        shape = (self.n_blocks + 1, self.block_size,
                 cfg.n_kv_heads, cfg.head_dim)
        self.pools = {
            "k": [jnp.zeros(shape, cfg.jdtype) for _ in range(cfg.n_layers)],
            "v": [jnp.zeros(shape, cfg.jdtype) for _ in range(cfg.n_layers)],
        }
        self._lock = threading.Lock()
        self._free = list(range(1, self.n_blocks + 1))

    def blocks_for(self, n_tokens):
        """Blocks a sequence of ``n_tokens`` total (prompt + generation
        budget) reserves."""
        return -(-int(n_tokens) // self.block_size)

    def alloc(self, n):
        """Reserve ``n`` blocks; returns the block index list or None
        when the pool cannot satisfy the reservation (admission
        backpressure — the caller retries once completions free blocks)."""
        n = int(n)
        with self._lock:
            if n > len(self._free):
                return None
            taken = self._free[:n]
            del self._free[:n]
            self._gauges_locked()
            return taken

    def release(self, blocks):
        """Return a reservation to the pool (idempotent callers pass each
        list exactly once; double-free is a bug we guard with a set check
        in debug runs, not in the hot path)."""
        if not blocks:
            return
        with self._lock:
            self._free.extend(blocks)
            self._gauges_locked()

    @property
    def free_blocks(self):
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self):
        with self._lock:
            return self.n_blocks - len(self._free)

    def _gauges_locked(self):
        if self.registry is None:
            return
        free = len(self._free)
        self.registry.set("ctpu_lm_kv_blocks_used", None,
                          self.n_blocks - free,
                          help_=_KV_HELP["ctpu_lm_kv_blocks_used"])
        self.registry.set("ctpu_lm_kv_blocks_free", None, free,
                          help_=_KV_HELP["ctpu_lm_kv_blocks_free"])

    def set_registry(self, registry):
        """Late-bind a metrics registry (the engine learns its server's
        registry at add_model time) and publish the current gauges."""
        with self._lock:
            self.registry = registry
            self._gauges_locked()
