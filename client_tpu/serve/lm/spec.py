"""Speculative decoding: model-free drafters + per-lane adaptive-k
control for the LM engine's draft/verify path.

Speculative decoding turns one decode tick into up to ``k + 1``
delivered tokens: a cheap host-side *drafter* proposes ``k``
continuation tokens, the engine scores all of them (plus the pending
input token) in ONE batched paged-attention pass (``engine._verify_tick``
— the multi-position generalization of ``_decode_tick``), and an
acceptance rule keeps the longest valid prefix:

- **greedy lanes** (temperature 0): a draft position is accepted iff it
  equals the argmax of the target logits there — the accepted prefix plus
  the argmax correction token reconstructs the plain-decode output
  byte-exactly, so speculation changes latency, never content.  One
  numerics caveat: the verify tick and the decode tick are different XLA
  programs (width ``w`` vs width 1), so their logits can differ by a few
  ulps of the compute dtype.  In float32 that never flips an argmax in
  practice; in bfloat16 a near-tie (top-2 margin at the ~1-ulp level,
  e.g. 1/64 at logit magnitude 2) can resolve differently — the output
  is still an exact greedy decode *of the verify pass's logits*, the
  same equivalence class every batched-verify implementation ships;
- **temperature lanes**: distribution-preserving rejection sampling for
  point-mass (deterministic) drafters — draft token ``x`` at a position
  with target probability ``p(x)`` (after the lane's top-k filter and
  temperature, exactly ``engine._select_token``'s distribution) is
  accepted with probability ``p(x)``; on rejection the correction token
  samples the residual (``p`` with ``x``'s mass removed, renormalized),
  which makes every delivered token an exact draw from the target
  distribution [Leviathan et al. 2023 / Chen et al. 2023, specialized to
  a deterministic proposal].

The drafters here need no second model (the interface is shaped so a
small draft model CAN plug in later via the device-placement layer):

- :class:`NgramDrafter` — prompt-lookup decoding: match the longest
  suffix (up to ``n`` tokens) of the generated history against the
  prompt + history and propose the continuation of the most recent
  prior occurrence.  Strong on the shared-prefix / extraction / code
  workloads where output echoes input.
- :class:`BigramDrafter` — a static greedy-bigram table seeded from the
  prompt at admission: propose by chaining each token's most frequent
  prompt successor.  Cheaper than n-gram search, weaker matches.

Adaptive k (:class:`LaneSpec`, one per active lane): a rolling
acceptance window shrinks ``k`` (halving; 1 -> 0 disables; a window
with ZERO accepts disables outright — the drafter has no signal, so
walking down just wastes verifies) when the drafter keeps missing, so
an adversarial prompt degrades to plain decode — the engine skips
drafting AND the verify dispatch entirely for disabled lanes, which is
the never-slower guarantee tests assert.  A
disabled lane re-probes with ``k = 1`` after ``retry_after`` plain
ticks (output statistics can drift into draftable territory), and a lane
whose window shows high acceptance grows ``k`` back toward the
configured maximum.
"""

import numpy as np

__all__ = [
    "Drafter",
    "NgramDrafter",
    "BigramDrafter",
    "make_drafter",
    "SpecConfig",
    "LaneSpec",
]


class Drafter:
    """Draft-token proposer interface (host-side, stateless across
    lanes: per-lane state lives in whatever ``begin`` returns).

    ``begin(prompt_row)`` runs once at lane activation and returns the
    drafter's per-lane state (any object; None is fine).  ``propose``
    is called on the scheduler thread with the CURRENT token history
    (prompt + every delivered token, as one int32 row — the last entry
    is the next tick's input token) and returns up to ``k`` proposed
    continuation tokens.  Returning ``[]`` means "no draft": the lane
    rides the pass as plain decode at zero extra cost.

    A model-backed drafter slots in here later: ``begin`` prefills the
    draft model, ``propose`` runs its (cheap) autoregressive loop.
    """

    name = "null"

    def begin(self, prompt_row):
        return None

    def propose(self, state, history, k):
        return []


class NgramDrafter(Drafter):
    """Prompt-lookup drafter: propose the continuation of the most
    recent prior occurrence of the history's longest matching suffix.

    For match lengths ``m = n .. 1``: find the latest position where the
    last ``m`` tokens of ``history`` previously occurred (vectorized
    sliding-window compare — the history is prompt + generation, a few
    hundred tokens, so this is microseconds) and propose the ``k``
    tokens that followed.  Longer matches are tried first: they predict
    the continuation far more reliably.
    """

    name = "ngram"

    def __init__(self, n=3, min_match=1):
        if n < 1:
            raise ValueError("ngram n must be >= 1")
        self.n = int(n)
        self.min_match = max(1, int(min_match))

    def propose(self, state, history, k):
        h = np.asarray(history, np.int32)
        t = h.shape[0]
        if k <= 0 or t < self.min_match + 1:
            return []
        for m in range(min(self.n, t - 1), self.min_match - 1, -1):
            pat = h[t - m:]
            # candidate starts 0 .. t-m-1: strictly before the suffix
            # itself, so a match always has at least one continuation
            # token
            wins = np.lib.stride_tricks.sliding_window_view(h, m)[:t - m]
            hits = np.nonzero((wins == pat).all(axis=1))[0]
            if hits.size:
                start = int(hits[-1]) + m  # most recent occurrence
                return h[start:start + k].tolist()
        return []


class BigramDrafter(Drafter):
    """Static greedy-bigram drafter: ``begin`` builds a token -> most
    frequent successor table from the prompt; ``propose`` chains it
    greedily from the last history token.  No per-token search at
    propose time — the cheapest possible drafter."""

    name = "bigram"

    def begin(self, prompt_row):
        row = np.asarray(prompt_row, np.int32)
        counts = {}
        for cur, nxt in zip(row[:-1].tolist(), row[1:].tolist()):
            slot = counts.setdefault(cur, {})
            slot[nxt] = slot.get(nxt, 0) + 1
        return {
            cur: max(succ.items(), key=lambda kv: (kv[1], -kv[0]))[0]
            for cur, succ in counts.items()
        }

    def propose(self, state, history, k):
        if not state or k <= 0 or len(history) == 0:
            return []
        out = []
        cur = int(history[-1])
        while len(out) < k:
            nxt = state.get(cur)
            if nxt is None:
                break
            out.append(nxt)
            cur = nxt
        return out


_DRAFTERS = {"ngram": NgramDrafter, "bigram": BigramDrafter}


def make_drafter(name, **kwargs):
    """Drafter registry lookup (``"ngram"`` / ``"bigram"``)."""
    try:
        cls = _DRAFTERS[str(name)]
    except KeyError:
        raise ValueError(
            f"unknown drafter {name!r} (have {sorted(_DRAFTERS)})"
        ) from None
    return cls(**kwargs)


class SpecConfig:
    """Parsed per-model speculative-decoding policy.

    Accepts the model-config block
    ``speculative={"k": 4, "drafter": "ngram", ...}`` (also a bare int
    as ``k``, or ``True`` for all defaults); ``drafter`` may be a
    registry name or a :class:`Drafter` instance (tests inject
    adversarial drafters that way).  Knobs:

    - ``k``: maximum draft tokens per verify tick (>= 1);
    - ``min_rate``: rolling acceptance rate below which a lane's k
      halves (1 -> 0 disables speculation for that lane);
    - ``grow_rate``: rate at or above which a backed-off lane's k
      doubles back toward ``k``;
    - ``window``: verify rounds per rolling-acceptance decision;
    - ``retry_after``: plain decode ticks a disabled lane waits before
      re-probing with k = 1.
    """

    __slots__ = ("k", "drafter", "min_rate", "grow_rate", "window",
                 "retry_after")

    def __init__(self, k=4, drafter="ngram", min_rate=0.35,
                 grow_rate=0.75, window=8, retry_after=128):
        self.k = int(k)
        if self.k < 1:
            raise ValueError("speculative k must be >= 1")
        self.drafter = (
            drafter if isinstance(drafter, Drafter)
            else make_drafter(drafter)
        )
        self.min_rate = float(min_rate)
        self.grow_rate = float(grow_rate)
        self.window = max(1, int(window))
        self.retry_after = max(1, int(retry_after))

    @classmethod
    def parse(cls, spec):
        """``None``/falsy -> None (speculation off); otherwise a
        SpecConfig from a config block / int / True / SpecConfig."""
        if not spec:
            return None
        if isinstance(spec, cls):
            return spec
        if spec is True:
            return cls()
        if isinstance(spec, (int, np.integer)):
            return cls(k=spec)
        if isinstance(spec, dict):
            extra = set(spec) - {
                "k", "drafter", "min_rate", "grow_rate", "window",
                "retry_after",
            }
            if extra:
                raise ValueError(
                    f"unknown speculative options: {sorted(extra)}"
                )
            return cls(**spec)
        raise TypeError(f"bad speculative config: {spec!r}")


class LaneSpec:
    """One lane's speculative state: drafter state + the adaptive-k
    controller.  Owned by the engine's scheduler thread; created at
    lane activation, dropped at retire (a resumed/preempted stream
    rebuilds it from the prompt — the rolling window restarts, which
    only delays re-disabling by one window)."""

    __slots__ = ("cfg", "state", "k", "_prop", "_acc", "_rounds",
                 "_idle")

    def __init__(self, cfg, prompt_row):
        self.cfg = cfg
        self.state = cfg.drafter.begin(prompt_row)
        self.k = cfg.k
        self._prop = 0
        self._acc = 0
        self._rounds = 0
        self._idle = 0  # plain ticks while disabled (re-probe timer)

    def draft(self, history):
        """Up to ``self.k`` proposed tokens ([] when disabled or the
        drafter has nothing)."""
        if self.k <= 0:
            return []
        toks = self.cfg.drafter.propose(self.state, history, self.k)
        return [int(t) for t in toks[:self.k]]

    def note_plain(self):
        """One plain decode tick ran for this lane; a disabled lane
        re-probes with k = 1 after ``retry_after`` of these."""
        if self.k > 0:
            return
        self._idle += 1
        if self._idle >= self.cfg.retry_after:
            self.k = 1
            self._idle = 0
            self._prop = self._acc = self._rounds = 0

    def note(self, proposed, accepted):
        """One verify round's outcome; steps k on a full window."""
        if proposed <= 0:
            return
        self._prop += int(proposed)
        self._acc += int(accepted)
        self._rounds += 1
        if self._rounds < self.cfg.window:
            return
        rate = self._acc / max(self._prop, 1)
        if self._acc == 0:
            # a FULLY rejected window is qualitatively different from a
            # low rate: the drafter has no signal at all here, so walking
            # k down (3 windows of wasted verifies) buys nothing — drop
            # straight to disabled and let the re-probe timer recover.
            # Healthy workloads never hit this (measured zero-accept
            # streaks top out well under a window), low-but-nonzero ones
            # take the gentle halving path below.
            self.k = 0
            self._idle = 0
        elif rate < self.cfg.min_rate:
            self.k //= 2  # 1 -> 0 disables; note_plain re-probes later
            self._idle = 0
        elif rate >= self.cfg.grow_rate and self.k < self.cfg.k:
            self.k = min(self.cfg.k, self.k * 2)
        self._prop = self._acc = 0
        self._rounds = 0
