"""Continuous-batching LM serving subsystem.

The production-grade successor of the fixed-lane prototype that used to
live inside ``serve/models/continuous.py`` (which now re-exports this
package's engine under its old names).  Four pillars:

- **prompt-length bucketing** (:mod:`.policy`) — prompts pad to a small
  geometric set of prefill widths so the compiled prefill-executable
  count is bounded by ``len(buckets)`` instead of growing with every
  novel prompt length;
- **chunked prefill** (:class:`.engine.LmEngine`) — prefill dispatches in
  fixed-width chunks interleaved 1:1 with decode ticks, so one novel
  long prompt can no longer freeze every active token stream for the
  length of its prefill (or its XLA compile);
- **paged KV cache** (:mod:`.kv`) — a block-table KV pool with
  fixed-size blocks and static shapes; HBM is pooled across lanes and
  requests reserve only the blocks their own ``prompt + max_tokens``
  needs, instead of every lane pinning ``max_seq`` rows forever;
- **lane autoscaling + per-tenant lane quotas** — the engine steps
  between a small precompiled set of decode lane counts on sustained
  queue depth, and admission is tenant-aware so one tenant cannot occupy
  every decode lane while another waits;
- **prefix cache** (:mod:`.prefix`) — a radix trie over token-block
  chains adopts cached full prompt-prefix blocks BY REFERENCE at
  admission (per-block refcounts in :mod:`.kv`), so chunked prefill
  starts at the first non-cached block; retiring requests hand their
  prompt blocks to the cache (LRU, evicted only under pool pressure);
- **preemption / swap** (:class:`.engine.LmEngine`) — under pool
  exhaustion with a strictly higher-priority tenant waiting, the
  lowest-priority lane swaps its KV to a bounded host-side store (or
  drops it for recompute), its stream pausing — not erroring — until
  blocks free up, byte-exact with an unpreempted run on the swap path.

Per-lane sampling (temperature / top-k via per-lane RNG keys inside the
jitted tick) removes the old "greedy only" limitation.
"""

from client_tpu.serve.lm.engine import LmEngine
from client_tpu.serve.lm.kv import KvBlockPool
from client_tpu.serve.lm.policy import (
    LaneAutoscaler,
    bucket_for,
    geometric_buckets,
    pad_prompt,
)
from client_tpu.serve.lm.prefix import PrefixCache

__all__ = [
    "LmEngine",
    "KvBlockPool",
    "LaneAutoscaler",
    "PrefixCache",
    "bucket_for",
    "geometric_buckets",
    "pad_prompt",
]
