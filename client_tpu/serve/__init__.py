"""In-process KServe-v2 server: model runtime + HTTP/gRPC frontends.

Dual purpose (SURVEY.md §4 "hermetic fake server" + the TPU serving path for
benchmarks). Typical use:

    from client_tpu.serve import Server
    with Server() as server:
        client = client_tpu.http.InferenceServerClient(server.http_address)
        ...

or standalone: ``python -m client_tpu.serve --http-port 8000 --grpc-port 8001``.
"""

from client_tpu.serve.builtins import default_models
from client_tpu.serve.model_runtime import (
    InferenceEngine,
    Model,
    TensorSpec,
)


class Server:
    """Convenience wrapper starting HTTP (and optionally gRPC) frontends."""

    def __init__(
        self,
        models=None,
        http_port=0,
        grpc_port=None,
        host="127.0.0.1",
        verbose=False,
        with_default_models=True,
        max_inflight=None,
        response_cache=None,
        coalescing=False,
        qos=None,
        fleet=None,
        slo=None,
    ):
        all_models = list(models or [])
        if with_default_models:
            all_models.extend(default_models())
        self.engine = InferenceEngine(
            all_models,
            max_inflight=max_inflight,
            response_cache=response_cache,
            coalescing=coalescing,
            qos=qos,
            fleet=fleet,
            slo=slo,
        )
        self._http = None
        self._grpc = None
        self._http_port = http_port
        self._grpc_port = grpc_port
        self._host = host
        self._verbose = verbose

    @property
    def http_address(self):
        return self._http.address if self._http else None

    @property
    def grpc_address(self):
        return self._grpc.address if self._grpc else None

    def start(self):
        from client_tpu.serve.http_server import HttpFrontend

        self._http = HttpFrontend(
            self.engine, self._host, self._http_port, self._verbose
        ).start()
        if self._grpc_port is not None:
            from client_tpu.serve.grpc_server import GrpcFrontend

            self._grpc = GrpcFrontend(
                self.engine, self._host, self._grpc_port, self._verbose
            ).start()
        return self

    def stop(self):
        if self._http:
            self._http.stop()
        if self._grpc:
            self._grpc.stop()
        self.engine.close()

    def drain(self, timeout_s=None):
        """Graceful shutdown: flip ``/v2/health/ready`` (and gRPC
        ServerReady) to not-ready, reject new inference with retryable
        503/UNAVAILABLE, finish in-flight work, then stop both frontends.
        Returns True when every in-flight request finished within
        *timeout_s*."""
        drained = self.engine.drain(timeout_s)
        self.stop()
        return drained

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
