"""SLO watchdog: streaming per-model/per-tenant latency quantiles with
configurable objectives, breach counters, and flight-recorder dumps.

The reference stack treats statistics introspection as a protocol
surface; this module closes the loop — the server itself knows its
objectives and makes breaches self-documenting:

- **sketches, not sample lists**: latency lands in a
  :class:`LatencySketch` — a fixed geometric-bucket digest (~60 ints).
  Constant memory per (model, tenant) key, O(1) observe, and MERGEABLE:
  adding two sketches' counts merges their distributions exactly, which
  is what makes the two-window rotation and any future cross-replica
  aggregation correct by construction.
- **sliding window**: each key keeps a current and a previous sketch,
  rotated every ``window_s``; quantiles read over their merge, so a
  spike ages out instead of polluting the quantile forever.
- **objectives**: ``{model_or_"*": {"p99_ms": float, "error_rate":
  float}}``.  A key whose windowed p99 (or error rate) exceeds its
  objective — with at least ``min_samples`` observations — increments
  ``ctpu_slo_breaches_total{model,tenant,kind}`` and triggers a
  flight-recorder dump (rate-limited to one per ``dump_interval_s``),
  so the postmortem artifact exists the moment the SLO is broken.
- **gauges**: every check exports ``ctpu_slo_p50_ms`` / ``_p95_ms`` /
  ``_p99_ms`` / ``ctpu_slo_error_rate`` per (model, tenant), scrapeable
  from /metrics next to the request counters they summarize.

Errors counted against the error-rate objective are SERVER faults
(5xx/transport); 4xx rejections are the client's problem and only count
as latency samples.  The engine calls :meth:`SloWatchdog.observe` once
per request — one lock and one bucket bisect, far below the 2%% tracing
overhead budget.
"""

import bisect
import math
import threading
import time
from collections import OrderedDict

from client_tpu.serve.metrics import SLO_HELP

__all__ = ["LatencySketch", "SloWatchdog", "BOUNDS_MS"]

# Geometric bucket bounds (milliseconds): 0.05ms .. ~32s with 1.25x
# growth — <=12.5% relative quantile error across the whole serving
# range, in 60 integers.
_RATIO = 1.25
BOUNDS_MS = tuple(0.05 * _RATIO ** i for i in range(60))


class LatencySketch:
    """Compact mergeable latency digest over fixed geometric buckets."""

    __slots__ = ("counts", "count", "errors", "sum_ms", "max_ms")

    def __init__(self):
        self.counts = [0] * (len(BOUNDS_MS) + 1)  # +Inf tail
        self.count = 0
        self.errors = 0
        self.sum_ms = 0.0
        self.max_ms = 0.0

    def observe(self, latency_ms, error=False):
        self.counts[bisect.bisect_left(BOUNDS_MS, latency_ms)] += 1
        self.count += 1
        self.sum_ms += latency_ms
        if latency_ms > self.max_ms:
            self.max_ms = latency_ms
        if error:
            self.errors += 1

    def merge(self, other):
        """Fold *other* into self (exact: buckets are shared)."""
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.errors += other.errors
        self.sum_ms += other.sum_ms
        self.max_ms = max(self.max_ms, other.max_ms)
        return self

    def merged(self, other):
        out = LatencySketch()
        out.merge(self)
        if other is not None:
            out.merge(other)
        return out

    def quantile(self, q):
        """The q-quantile's bucket upper bound in ms (0 when empty) —
        an overestimate by at most one bucket ratio, the conservative
        side for an SLO check."""
        if self.count <= 0:
            return 0.0
        rank = max(int(math.ceil(float(q) * self.count)), 1)
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                if i < len(BOUNDS_MS):
                    return BOUNDS_MS[i]
                return self.max_ms  # +Inf tail: the observed max
        return self.max_ms

    def error_rate(self):
        return self.errors / self.count if self.count else 0.0

    def to_json(self):
        return {
            "count": self.count,
            "errors": self.errors,
            "sum_ms": self.sum_ms,
            "max_ms": self.max_ms,
            "counts": list(self.counts),
        }


class _Key:
    """Per-(model, tenant) window state."""

    __slots__ = ("cur", "prev", "rotated_at", "since_check", "breaches",
                 "last_quantiles")

    def __init__(self):
        self.cur = LatencySketch()
        self.prev = None
        self.rotated_at = time.monotonic()
        self.since_check = 0
        self.breaches = 0
        self.last_quantiles = {}


class SloWatchdog:
    """Streaming SLO evaluation over per-(model, tenant) sketches.

    ``objectives`` maps a model name (or ``"*"`` for every model) to
    ``{"p99_ms": float, "error_rate": float}`` — either key optional.
    With no objectives the watchdog still exports the quantile gauges
    (observation-only mode: the engine enables it by default).
    """

    def __init__(self, objectives=None, registry=None, flight=None,
                 window_s=60.0, min_samples=32, check_every=16,
                 dump_interval_s=30.0, max_keys=512):
        self.objectives = dict(objectives or {})
        self.registry = registry
        self.flight = flight
        self.window_s = float(window_s)
        self.min_samples = int(min_samples)
        self.check_every = max(int(check_every), 1)
        self.dump_interval_s = float(dump_interval_s)
        self.max_keys = int(max_keys)
        self._lock = threading.Lock()
        self._keys = OrderedDict()  # (model, tenant) -> _Key
        self._last_dump = 0.0
        self.breaches = 0

    def objective_for(self, model):
        """The objective block applying to *model* (exact name wins over
        the ``"*"`` default), or None."""
        return self.objectives.get(model, self.objectives.get("*"))

    # -- feeding -----------------------------------------------------------

    def observe(self, model, tenant, latency_s, error=False):
        """Record one finished request.  Cheap by contract (one lock,
        one bisect); every ``check_every`` observations of a key the
        objectives are evaluated over the merged two-window sketch."""
        latency_ms = float(latency_s) * 1e3
        key = (str(model), str(tenant))
        now = time.monotonic()
        with self._lock:
            entry = self._keys.get(key)
            if entry is None:
                entry = self._keys[key] = _Key()
                # insertion-order eviction, not strict LRU: the key set
                # is model x tenant (tiny in practice), and per-observe
                # move_to_end would tax the hot path for an eviction
                # that essentially never fires
                while len(self._keys) > self.max_keys:
                    self._keys.popitem(last=False)
            if now - entry.rotated_at > self.window_s:
                entry.prev = entry.cur
                entry.cur = LatencySketch()
                entry.rotated_at = now
            entry.cur.observe(latency_ms, error=error)
            entry.since_check += 1
            if entry.since_check < self.check_every:
                return
            entry.since_check = 0
            window = entry.cur.merged(entry.prev)
        # evaluation runs OUTSIDE the lock: gauge export and a possible
        # flight dump must not serialize concurrent request completions
        self._check_key(key, entry, window)

    # -- evaluation --------------------------------------------------------

    def _check_key(self, key, entry, window):
        model, tenant = key
        quantiles = {
            "p50_ms": window.quantile(0.50),
            "p95_ms": window.quantile(0.95),
            "p99_ms": window.quantile(0.99),
            "error_rate": window.error_rate(),
            "count": window.count,
        }
        entry.last_quantiles = quantiles
        labels = {"model": model, "tenant": tenant}
        if self.registry is not None:
            for name, field in (
                ("ctpu_slo_p50_ms", "p50_ms"),
                ("ctpu_slo_p95_ms", "p95_ms"),
                ("ctpu_slo_p99_ms", "p99_ms"),
                ("ctpu_slo_error_rate", "error_rate"),
            ):
                self.registry.set(
                    name, labels, quantiles[field], help_=SLO_HELP[name]
                )
        objective = self.objective_for(model)
        if objective is None or window.count < self.min_samples:
            return
        breaches = []
        p99_obj = objective.get("p99_ms")
        if p99_obj is not None and quantiles["p99_ms"] > float(p99_obj):
            breaches.append(("p99_ms", quantiles["p99_ms"], float(p99_obj)))
        err_obj = objective.get("error_rate")
        if err_obj is not None and quantiles["error_rate"] > float(err_obj):
            breaches.append(
                ("error_rate", quantiles["error_rate"], float(err_obj))
            )
        for kind, value, bound in breaches:
            self._breach(model, tenant, entry, kind, value, bound,
                         quantiles)

    def _breach(self, model, tenant, entry, kind, value, bound, quantiles):
        with self._lock:
            entry.breaches += 1
            self.breaches += 1
            now = time.monotonic()
            want_dump = (
                self.flight is not None
                and now - self._last_dump >= self.dump_interval_s
            )
            if want_dump:
                self._last_dump = now
        if self.registry is not None:
            self.registry.inc(
                "ctpu_slo_breaches_total",
                {"model": model, "tenant": tenant, "kind": kind},
                help_=SLO_HELP["ctpu_slo_breaches_total"],
            )
        flight = self.flight
        if flight is not None:
            flight.note(
                "slo_breach", model=model, tenant=tenant,
                objective_kind=kind, value=value, objective=bound,
                window=quantiles,
            )
            if want_dump:
                flight.dump("slo_breach")

    # -- introspection -----------------------------------------------------

    def check_now(self):
        """Force an objective pass over every key (tests, bench rounds,
        pre-scrape hooks) and return :meth:`summary`."""
        with self._lock:
            items = [
                (key, entry, entry.cur.merged(entry.prev))
                for key, entry in self._keys.items()
            ]
        for key, entry, window in items:
            self._check_key(key, entry, window)
        return self.summary()

    def summary(self):
        """``{"model|tenant": {p50_ms, p95_ms, p99_ms, error_rate,
        count, breaches}}`` over the latest checked windows (JSON-safe —
        bench rounds record this block)."""
        with self._lock:
            out = {}
            for (model, tenant), entry in self._keys.items():
                q = dict(entry.last_quantiles)
                if not q:
                    window = entry.cur.merged(entry.prev)
                    q = {
                        "p50_ms": window.quantile(0.50),
                        "p95_ms": window.quantile(0.95),
                        "p99_ms": window.quantile(0.99),
                        "error_rate": window.error_rate(),
                        "count": window.count,
                    }
                q["breaches"] = entry.breaches
                out[f"{model}|{tenant}"] = q
            return out
