"""Continuous batching for decoupled LM token streaming — compat surface.

The fixed-lane prototype that lived here grew into the
``client_tpu.serve.lm`` subsystem (paged KV cache, bucketed + chunked
prefill interleaved with decode, lane autoscaling, per-lane sampling,
tenant-aware lane admission).  This module keeps the original names and
submit/cancel/stream surface so existing callers and tests are
untouched:

- :class:`ContinuousLmScheduler` IS :class:`client_tpu.serve.lm.LmEngine`
  (``submit(prompt, max_tokens) -> (queue, handle)``, ``cancel``,
  ``close``, the ``CLOSE`` sentinel);
- :class:`BatchedLmRunner` is the ``stream()`` provider
  lm_streaming_batched_model plugs into — now with per-request
  temperature / top-k / seed (per-lane RNG inside the jitted tick
  removed the old "greedy only" 400) and a ``tenant`` identity that
  feeds per-tenant decode-lane quotas.  Engine-level features arriving
  after the split (speculative decoding via
  ``lm_streaming_batched_model(speculative=...)``, prefix-cache
  adoption, lane autoscaling) pass through this surface untouched:
  they live below submit/cancel/stream.

See ``client_tpu/serve/lm/`` for the engine internals and README
"LLM serving / continuous batching" for the design.
"""

import numpy as np

from client_tpu.serve.lm.engine import _CLOSE, _TOPK_CAP, LmEngine
from client_tpu.utils import InferenceServerException

# the engine, under its historical serving-path name
ContinuousLmScheduler = LmEngine


class BatchedLmRunner:
    """Drop-in ``stream()`` provider backed by the continuous-batching
    engine — signature-compatible with language._LmRunner.stream so the
    batched model reuses lm_streaming_model verbatim.  Per-request
    sampling (temperature / top_k / seed) runs inside the jitted tick
    with per-lane RNG keys; temperature 0 lanes take the on-device
    argmax, so mixed greedy/sampled batches share one executable."""

    def __init__(self, params, cfg, max_slots=4, eos_id=None,
                 check_prompt=None, **engine_kwargs):
        self.cfg = cfg
        self.scheduler = LmEngine(
            params, cfg, max_slots=max_slots, eos_id=eos_id,
            check_prompt=check_prompt, **engine_kwargs,
        )

    def stream(self, tokens, max_tokens, temperature=0.0, seed=0,
               top_k=0, tenant=""):
        if int(top_k) > _TOPK_CAP:
            # the jitted tick's per-lane filter has a static width: a
            # silently-truncated k would sample a different distribution
            # than the client asked for
            raise InferenceServerException(
                f"top_k {int(top_k)} exceeds the engine's static cap of "
                f"{_TOPK_CAP}; use top_k <= {_TOPK_CAP} or 0 (unfiltered)",
                status="400",
            )
        if self.scheduler.check_prompt is not None:
            self.scheduler.check_prompt(
                int(np.asarray(tokens).reshape(-1).shape[0])
            )
        q, handle = self.scheduler.submit(
            tokens, max_tokens, temperature=temperature, top_k=top_k,
            seed=seed, tenant=tenant,
        )
        try:
            while True:
                tok = q.get()
                if tok is _CLOSE:
                    return
                yield tok
        finally:
            self.scheduler.cancel(handle)
