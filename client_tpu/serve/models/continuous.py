"""Slot-based continuous batching for decoupled LM token streaming.

The Orca/vLLM idea in its static-shape TPU form: a fixed batch of
``max_slots`` decode lanes runs ONE jitted ``decode_step`` per tick across
every active stream.  ``transformer.decode_step`` is already per-row
batched with heterogeneous positions (``cache["len"]`` is ``[B]``; rope,
the KV scatter, and the attention mask are all per-row), so concurrent
streams share each matmul instead of serializing whole decode programs —
aggregate tokens/sec scales with active lanes, where per-request decode
(one ``generate()`` per stream) stays flat.

TPU-first constraints honored:
- Static shapes everywhere: the lane count is fixed at construction; idle
  lanes compute masked garbage that nobody reads (no dynamic batch growth,
  no recompiles).  Admission splices a prefilled request's KV rows into the
  batched cache with ``dynamic_update_slice`` at a *traced* slot index —
  one executable regardless of slot.
- Async dispatch: the scheduler thread dispatches decode ticks ahead of
  readback; per-tick token vectors drain through a ``copy_to_host_async``
  pipeline exactly like ``transformer.generate`` (depth ``readback_depth``),
  so a high-RTT link bounds throughput at ~depth ticks/RTT, not 1/RTT.
- Greedy selection stays on device (argmax inside the jitted tick).

Reference analog: none — the reference is a client; its Llama config
(BASELINE config 5) points at a server whose continuous batching lives in
the backend.  Here the TPU-native server owns it.
"""

import functools
import queue
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from client_tpu.serve.models import transformer as tfm

# sentinel object closing a stream's token queue
_CLOSE = object()

# placed-marker for a handle cancelled while its prefill dispatch was in
# flight (admission runs outside _cv); _admit sees it and closes the queue
_CANCELLED = object()


class _Slot:
    __slots__ = ("gen", "active", "queue", "remaining", "produced")

    def __init__(self):
        self.gen = 0        # bumped on every (re)assignment and cancel
        self.active = False
        self.queue = None   # per-request token queue
        self.remaining = 0  # tokens still to produce
        self.produced = 0


class ContinuousLmScheduler:
    """Continuous-batching decode scheduler over a fixed lane count.

    ``submit(prompt_tokens, max_tokens)`` returns a ``queue.Queue`` that
    yields int token ids and finally the ``CLOSE`` sentinel; ``cancel``
    releases a lane early (abandoned client streams).  Greedy decoding
    only — the batched tick selects argmax on device; per-request
    temperature would need per-lane RNG lanes (future work).
    """

    CLOSE = _CLOSE

    def __init__(self, params, cfg, max_slots=4, readback_depth=8,
                 eos_id=None, check_prompt=None):
        self.params = params
        self.cfg = cfg
        self.max_slots = int(max_slots)
        self.depth = max(int(readback_depth), 0)
        self.eos_id = eos_id
        self.check_prompt = check_prompt  # optional prompt validator
        self._slots = [_Slot() for _ in range(self.max_slots)]
        self._pending = []  # (prompt np.int32[1,T], max_tokens, q)
        self._cv = threading.Condition()
        self._closed = False

        # device state allocates lazily with the thread: a Server that
        # never routes a request here must not pin HBM for the lane cache
        self._cache = None
        self._tokens = None
        self._prefill = jax.jit(functools.partial(tfm.prefill, cfg=cfg))

        n_layers = cfg.n_layers

        def adopt(cache, single, tokens, slot, first_token):
            """Splice a prefilled batch-1 cache into lane ``slot`` and set
            its next input token — slot is a traced index, one executable."""
            out = {
                "k": [
                    lax.dynamic_update_slice(
                        cache["k"][i], single["k"][i], (slot, 0, 0, 0)
                    )
                    for i in range(n_layers)
                ],
                "v": [
                    lax.dynamic_update_slice(
                        cache["v"][i], single["v"][i], (slot, 0, 0, 0)
                    )
                    for i in range(n_layers)
                ],
                "len": cache["len"].at[slot].set(single["len"][0]),
            }
            return out, tokens.at[slot].set(first_token)

        self._adopt = jax.jit(adopt)

        def tick(params, tokens, cache):
            logits, cache = tfm.decode_step(params, tokens, cfg=cfg,
                                            cache=cache)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        self._tick = jax.jit(tick)
        self._thread = None  # started lazily on the first submit

    def _ensure_thread_locked(self):
        if self._thread is None:
            self._cache = tfm.init_cache(self.cfg, self.max_slots)
            self._tokens = jnp.zeros((self.max_slots,), jnp.int32)
            self._thread = threading.Thread(
                target=self._loop, name="lm-continuous-batcher", daemon=True
            )
            self._thread.start()

    # -- request side ------------------------------------------------------

    def submit(self, prompt_tokens, max_tokens):
        """Returns (token_queue, handle); the queue ends with CLOSE."""
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        # clamp like generate(): slot i's token goes to prompt_len + i
        max_tokens = min(int(max_tokens),
                         self.cfg.max_seq - prompt.shape[1])
        q = queue.Queue()
        if max_tokens <= 0:
            q.put(_CLOSE)
            return q, None
        entry = [prompt, max_tokens, q, None]  # [3] = (slot, gen) once admitted
        with self._cv:
            if self._closed:
                q.put(_CLOSE)
                return q, None
            self._ensure_thread_locked()
            self._pending.append(entry)
            self._cv.notify_all()
        return q, entry

    def cancel(self, handle):
        """Release a stream early (consumer went away)."""
        if handle is None:
            return
        with self._cv:
            # identity scan: entries hold numpy prompts, so `in`/`remove`
            # (which compare element-wise) would raise on array equality
            for i, entry in enumerate(self._pending):
                if entry is handle:
                    entry[2].put(_CLOSE)  # a reader must not hang on get()
                    del self._pending[i]
                    return
            placed = handle[3]
            if placed is None:
                # popped from _pending but not yet admitted: the prefill
                # dispatch is running outside _cv right now.  Mark the
                # handle; _admit closes the queue once the dispatch returns.
                handle[3] = _CANCELLED
                return
            if placed is _CANCELLED:
                return
            slot_idx, gen = placed
            slot = self._slots[slot_idx]
            if slot.active and slot.gen == gen:
                slot.active = False
                slot.gen += 1  # in-flight ticks for this lane drop on drain
                slot.queue.put(_CLOSE)  # a reader must not hang on get()

    def _release_all_locked(self):
        """Close every pending and active stream queue (caller holds _cv)."""
        for entry in self._pending:
            entry[2].put(_CLOSE)
        self._pending.clear()
        for slot in self._slots:
            if slot.active:
                slot.active = False
                slot.gen += 1
                slot.queue.put(_CLOSE)

    def close(self):
        with self._cv:
            self._closed = True
            self._release_all_locked()
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)

    # -- scheduler loop ----------------------------------------------------

    def _admit(self):
        """Move pending requests into free lanes (prefill + splice).

        The prefill dispatch runs OUTSIDE _cv: jax.jit compiles a fresh
        prefill executable per distinct prompt length, so a novel-length
        prompt would otherwise hold the lock for a full XLA compile
        (seconds) and head-of-line-block every submit()/cancel() caller.
        Only the pending-pop and slot bookkeeping need the lock — the
        device state (_cache/_tokens) is scheduler-thread-private.  Lanes
        admit one at a time; the scheduler is the only admitter, so a
        reserved slot_idx cannot be stolen while the lock is dropped.
        """
        while True:
            with self._cv:
                if self._closed or not self._pending:
                    return
                slot_idx = next(
                    (i for i, s in enumerate(self._slots) if not s.active),
                    None,
                )
                if slot_idx is None:
                    return
                entry = self._pending.pop(0)
                prompt, max_tokens, q = entry[0], entry[1], entry[2]
            try:
                single = tfm.init_cache(self.cfg, 1)
                logits, single = self._prefill(
                    self.params, jnp.asarray(prompt), cache=single
                )
                first = jnp.argmax(logits, axis=-1).astype(jnp.int32)[0]
                self._cache, self._tokens = self._adopt(
                    self._cache, single, self._tokens, slot_idx, first
                )
            except BaseException:
                # the entry is in neither _pending nor a slot here, so the
                # crash handler's _release_all_locked cannot see it — close
                # its stream before the exception kills the scheduler
                q.put(_CLOSE)
                raise
            with self._cv:
                if self._closed or entry[3] is _CANCELLED:
                    # consumer went away (or shutdown) during the dispatch:
                    # close the stream and leave the lane free — the spliced
                    # cache rows are inert, like any idle lane's garbage
                    q.put(_CLOSE)
                    continue
                slot = self._slots[slot_idx]
                slot.gen += 1
                slot.active = True
                slot.queue = q
                slot.remaining = max_tokens
                slot.produced = 0
                entry[3] = (slot_idx, slot.gen)
                # the prefill's own first token streams through the readback
                # pipeline like every tick token (single-lane entry)
                if hasattr(first, "copy_to_host_async"):
                    first.copy_to_host_async()
                self._inflight.append((first, ((slot_idx, slot.gen),)))

    def _drain_one(self):
        tokens_dev, snapshot = self._inflight.popleft()
        vals = np.asarray(tokens_dev).reshape(-1)
        with self._cv:
            for slot_idx, gen in snapshot:
                slot = self._slots[slot_idx]
                if not slot.active or slot.gen != gen:
                    continue  # cancelled/finished lane: stale tick token
                # full ticks carry one token PER LANE (index by slot);
                # single-lane prefill entries carry exactly one value
                token = int(vals[slot_idx]) if vals.size > 1 else int(vals[0])
                slot.queue.put(token)
                slot.produced += 1
                done = (
                    slot.produced >= slot.remaining
                    or (self.eos_id is not None and token == self.eos_id)
                )
                if done:
                    slot.queue.put(_CLOSE)
                    slot.active = False
                    slot.gen += 1

    def _loop(self):
        try:
            self._loop_inner()
        except Exception:
            # a dying scheduler must never strand consumers on q.get()
            with self._cv:
                self._release_all_locked()
                self._closed = True
            raise

    def _loop_inner(self):
        from collections import deque

        self._inflight = deque()
        while True:
            self._admit()  # takes/releases _cv itself; prefill outside it
            with self._cv:
                if self._closed:
                    break
                active = [
                    (i, s.gen) for i, s in enumerate(self._slots) if s.active
                ]
                if not active and not self._pending:
                    if self._inflight:
                        pass  # fall through to drain the tail
                    else:
                        self._cv.wait(timeout=0.1)
                        continue
            if active:
                self._tokens, self._cache = self._tick(
                    self.params, self._tokens, self._cache
                )
                if hasattr(self._tokens, "copy_to_host_async"):
                    self._tokens.copy_to_host_async()
                # full-batch snapshot: entry i maps to vals[slot_idx]
                self._inflight.append(
                    (self._tokens,
                     tuple((slot_idx, gen) for slot_idx, gen in active))
                )
            while len(self._inflight) > (self.depth if active else 0):
                self._drain_one()
        # shutdown: drop the in-flight tail (queues already closed)
        self._inflight.clear()


class BatchedLmRunner:
    """Drop-in ``stream()`` provider backed by ContinuousLmScheduler —
    signature-compatible with language._LmRunner.stream so the batched
    model reuses lm_streaming_model verbatim.  Greedy-only: the batched
    tick argmaxes on device, so a sampled request is rejected with a clear
    400 instead of silently decoding greedily."""

    def __init__(self, params, cfg, max_slots=4, eos_id=None,
                 check_prompt=None):
        self.cfg = cfg
        self.scheduler = ContinuousLmScheduler(
            params, cfg, max_slots=max_slots, eos_id=eos_id,
            check_prompt=check_prompt,
        )

    def stream(self, tokens, max_tokens, temperature=0.0, seed=0):
        if temperature and float(temperature) > 0.0:
            from client_tpu.utils import InferenceServerException

            raise InferenceServerException(
                "the continuous-batching LM decodes greedily (batched "
                "on-device argmax); use lm_streaming for sampled "
                "generation", status="400",
            )
        if self.scheduler.check_prompt is not None:
            self.scheduler.check_prompt(
                int(np.asarray(tokens).reshape(-1).shape[0])
            )
        q, handle = self.scheduler.submit(tokens, max_tokens)
        try:
            while True:
                tok = q.get()
                if tok is _CLOSE:
                    return
                yield tok
        finally:
            self.scheduler.cancel(handle)
