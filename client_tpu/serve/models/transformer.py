"""Decoder-only transformer LM — the framework's flagship served model.

This is the server-side model behind BASELINE.md config 5 (tokenizer→LLM
streaming inference with decoupled token-by-token responses) and the model
`__graft_entry__.py` exposes to the driver.  Llama-style architecture:
RMSNorm, rotary embeddings, grouped-query attention, SwiGLU MLP, untied LM
head.  Pure functional JAX:

- ``init_params(key, cfg)`` → pytree matching ``client_tpu.parallel.param_specs``
- ``forward(params, tokens, cfg)`` — full-sequence logits (training/prefill);
  ``attn_impl="ring"`` switches the attention to sequence-parallel ring
  attention over the mesh's ``sp`` axis for long-context sharding
- ``prefill`` / ``decode_step`` — KV-cache incremental decoding for the
  streaming serving path (static cache shape so every step hits the same
  compiled program)
- ``make_train_step(cfg, mesh)`` — jitted dp/tp/sp-sharded Adam training step
  (the multi-chip path the driver dry-runs)

TPU-first notes: weights and attention/MLP compute are bfloat16 on the MXU
with float32 softmax/norm/loss accumulations; shapes are static everywhere;
the decode loop is a fixed-shape program with `lax.dynamic_update_slice` cache
writes; sharding is annotation-only (GSPMD inserts the collectives).
"""

import collections
import dataclasses
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from client_tpu.ops.quant import matmul as _mm
from client_tpu.parallel.ring_attention import (
    plain_attention,
    ring_attention_sharded,
)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 1536
    max_seq: int = 1024
    rope_theta: float = 10000.0
    dtype: str = "bfloat16"
    # n_experts > 0 switches the FFN to a top-k-routed mixture of experts
    # (expert-parallel over the mesh's "ep" axis — parallel.param_specs)
    n_experts: int = 0
    top_k: int = 2
    # Switch-style load-balance aux loss coefficient (loss_fn adds it for
    # MoE configs; without it the router collapses onto few experts)
    router_aux_coef: float = 0.01

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)


def init_params(key, cfg):
    """Initialize a params pytree (layout documented in parallel.param_specs)."""
    dt = cfg.jdtype
    n_keys = 3 + cfg.n_layers * 8
    keys = iter(jax.random.split(key, n_keys))

    def dense(shape, fan_in):
        return jax.random.normal(next(keys), shape, dt) * float(fan_in ** -0.5)

    hd = cfg.head_dim
    layers = []
    for _ in range(cfg.n_layers):
        entry = {
            "attn": {
                "wq": dense((cfg.d_model, cfg.n_heads * hd), cfg.d_model),
                "wk": dense((cfg.d_model, cfg.n_kv_heads * hd), cfg.d_model),
                "wv": dense((cfg.d_model, cfg.n_kv_heads * hd), cfg.d_model),
                "wo": dense((cfg.n_heads * hd, cfg.d_model), cfg.n_heads * hd),
            },
            "ln_attn": jnp.ones((cfg.d_model,), dt),
            "ln_mlp": jnp.ones((cfg.d_model,), dt),
        }
        if cfg.n_experts > 0:
            e = cfg.n_experts
            entry["moe"] = {
                "router": dense((cfg.d_model, e), cfg.d_model),
                "w_gate": dense((e, cfg.d_model, cfg.d_ff), cfg.d_model),
                "w_up": dense((e, cfg.d_model, cfg.d_ff), cfg.d_model),
                "w_down": dense((e, cfg.d_ff, cfg.d_model), cfg.d_ff),
            }
        else:
            entry["mlp"] = {
                "w_gate": dense((cfg.d_model, cfg.d_ff), cfg.d_model),
                "w_up": dense((cfg.d_model, cfg.d_ff), cfg.d_model),
                "w_down": dense((cfg.d_ff, cfg.d_model), cfg.d_ff),
            }
        layers.append(entry)
    return {
        "embed": dense((cfg.vocab_size, cfg.d_model), cfg.d_model),
        "layers": layers,
        "ln_f": jnp.ones((cfg.d_model,), dt),
        "lm_head": dense((cfg.d_model, cfg.vocab_size), cfg.d_model),
    }


def _rms_norm(x, scale, eps=1e-5):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * lax.rsqrt(var + eps)).astype(x.dtype) * scale


def _rope(x, positions, theta):
    # x: [B,T,H,D]; positions: [B,T] or [T]
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if positions.ndim == 1:
        positions = positions[None, :]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,T,half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _repeat_kv(x, n_rep):
    if n_rep == 1:
        return x
    b, t, h, d = x.shape
    return jnp.repeat(x, n_rep, axis=2)


def _attention_block(layer, x, cfg, positions, mesh, attn_impl):
    """Full-sequence causal self-attention sublayer; returns (x, (k, v)) so
    prefill can capture the per-layer KV blocks for the cache."""
    b, t, _ = x.shape
    hd = cfg.head_dim
    h = _rms_norm(x, layer["ln_attn"])
    q = _mm(h, layer["attn"]["wq"]).reshape(b, t, cfg.n_heads, hd)
    k = _mm(h, layer["attn"]["wk"]).reshape(b, t, cfg.n_kv_heads, hd)
    v = _mm(h, layer["attn"]["wv"]).reshape(b, t, cfg.n_kv_heads, hd)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    n_rep = cfg.n_heads // cfg.n_kv_heads

    if attn_impl in ("ring", "ring_flash"):
        # "ring_flash": the same sp-sharded ring schedule with each step's
        # block pair computed by the Pallas flash kernel (O(block) memory
        # per step — the long-context sharded-training configuration)
        attn = ring_attention_sharded(
            q, _repeat_kv(k, n_rep), _repeat_kv(v, n_rep), mesh,
            impl="flash" if attn_impl == "ring_flash" else "plain",
        )
    elif attn_impl == "flash":
        # Pallas kernel (client_tpu.ops): no [T,T] score materialization —
        # the long-context single-shard path.  It has no partitioning rule,
        # so sp-sharded activations would be silently gathered: use "ring"
        # (which consumes the mesh) for sequence-parallel runs.
        if mesh is not None:
            raise ValueError(
                "attn_impl='flash' is single-shard; use attn_impl='ring' "
                "with a mesh"
            )
        from client_tpu.ops import flash_attention

        attn = flash_attention(q, _repeat_kv(k, n_rep), _repeat_kv(v, n_rep))
    else:
        attn = plain_attention(q, _repeat_kv(k, n_rep), _repeat_kv(v, n_rep))

    out = _mm(attn.reshape(b, t, cfg.n_heads * hd), layer["attn"]["wo"])
    return x + out, (k, v)


def _mlp_block(layer, x):
    h = _rms_norm(x, layer["ln_mlp"])
    gate = jax.nn.silu(_mm(h, layer["mlp"]["w_gate"]))
    up = _mm(h, layer["mlp"]["w_up"])
    return x + _mm(gate * up, layer["mlp"]["w_down"])


def _moe_block(layer, x, cfg):
    """Top-k-routed mixture-of-experts FFN, expert-parallel over ``ep``.

    Dense formulation: every expert computes on every token (stacked-weight
    einsums with the expert dim sharded over ep — each device runs its local
    experts on the MXU) and the router's top-k weights zero out unselected
    experts in the combine; the contraction over experts becomes a psum over
    ep inserted by GSPMD.  Compiler-friendly (static shapes, no gather/sort
    dispatch) and exact; capacity-based sparse dispatch is the big-scale
    optimization this trades away.
    """
    moe = layer["moe"]
    h = _rms_norm(x, layer["ln_mlp"])
    logits = (
        h.astype(jnp.float32) @ moe["router"].astype(jnp.float32)
    )  # [B,T,E]
    top_w, top_idx = lax.top_k(logits, cfg.top_k)
    top_w = jax.nn.softmax(top_w, axis=-1)  # renormalize over the selected k
    combine = jnp.sum(
        jax.nn.one_hot(top_idx, cfg.n_experts, dtype=jnp.float32)
        * top_w[..., None],
        axis=-2,
    )  # [B,T,E]
    g = jnp.einsum("btd,edf->ebtf", h, moe["w_gate"])
    u = jnp.einsum("btd,edf->ebtf", h, moe["w_up"])
    expert_out = jnp.einsum(
        "ebtf,efd->ebtd", jax.nn.silu(g) * u, moe["w_down"]
    )  # [E,B,T,D]
    out = jnp.einsum(
        "ebtd,bte->btd",
        expert_out.astype(jnp.float32),
        combine,
        preferred_element_type=jnp.float32,
    ).astype(x.dtype)
    # Switch-transformer load-balance loss: E * Σ_e (token fraction routed
    # to e) * (mean router prob of e); minimized (=1) at uniform routing
    probs = jax.nn.softmax(logits, axis=-1)
    frac = jnp.mean(
        jax.nn.one_hot(top_idx, cfg.n_experts, dtype=jnp.float32), axis=(0, 1, 2)
    )  # per-expert routed fraction over B*T*K; uniform router → 1/E each
    aux = cfg.n_experts * jnp.sum(frac * jnp.mean(probs, axis=(0, 1)))
    return x + out, aux


def _ffn_block(layer, x, cfg):
    """FFN (dense or MoE) → (residual output, router aux loss or 0)."""
    if "moe" in layer:
        return _moe_block(layer, x, cfg)
    return _mlp_block(layer, x), jnp.float32(0.0)


def forward(params, tokens, cfg, mesh=None, attn_impl="plain",
            with_aux=False):
    """Full-sequence causal LM: tokens [B,T] int32 → logits [B,T,V] f32.

    With ``with_aux=True`` returns ``(logits, aux)`` where aux is the mean
    per-layer router load-balance loss (0 for dense configs).
    """
    b, t = tokens.shape
    if mesh is not None:
        from client_tpu.ops.quant import is_quantized

        if is_quantized(params["lm_head"]):
            # the int8 pallas_call has no partitioning rule; GSPMD would
            # silently gather sharded activations into it (same hazard the
            # flash branch guards against)
            raise ValueError(
                "quantized params are single-device serving weights; "
                "dequantize or drop the mesh"
            )
    x = jnp.take(params["embed"], tokens, axis=0)
    if mesh is not None:
        x = lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("dp", "sp", None))
        )
    positions = jnp.arange(t)
    aux_total = jnp.float32(0.0)
    for layer in params["layers"]:
        x, _ = _attention_block(layer, x, cfg, positions, mesh, attn_impl)
        x, aux = _ffn_block(layer, x, cfg)
        aux_total = aux_total + aux
    x = _rms_norm(x, params["ln_f"])
    logits = _mm(x, params["lm_head"]).astype(jnp.float32)
    if mesh is not None:
        logits = lax.with_sharding_constraint(
            logits, NamedSharding(mesh, P("dp", "sp", "tp"))
        )
    if with_aux:
        return logits, aux_total / len(params["layers"])
    return logits


def init_cache(cfg, batch):
    """Static-shape KV cache: per layer k/v [B, max_seq, n_kv, head_dim]."""
    shape = (batch, cfg.max_seq, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": [jnp.zeros(shape, cfg.jdtype) for _ in range(cfg.n_layers)],
        "v": [jnp.zeros(shape, cfg.jdtype) for _ in range(cfg.n_layers)],
        "len": jnp.zeros((batch,), jnp.int32),
    }


def prefill(params, tokens, cfg, cache):
    """Run the prompt through the model, filling the cache from position 0.

    Returns (last-token logits [B,V], cache).
    """
    b, t = tokens.shape
    x = jnp.take(params["embed"], tokens, axis=0)
    positions = jnp.arange(t)
    for i, layer in enumerate(params["layers"]):
        x, (k, v) = _attention_block(layer, x, cfg, positions, None, "plain")
        cache["k"][i] = lax.dynamic_update_slice(
            cache["k"][i], k, (0, 0, 0, 0)
        )
        cache["v"][i] = lax.dynamic_update_slice(
            cache["v"][i], v, (0, 0, 0, 0)
        )
        x, _ = _ffn_block(layer, x, cfg)
    x = _rms_norm(x, params["ln_f"])
    logits = _mm(x[:, -1], params["lm_head"]).astype(jnp.float32)
    cache["len"] = jnp.full((b,), t, jnp.int32)
    return logits, cache


def decode_step(params, token, cfg, cache):
    """One incremental decode step: token [B] int32 → (logits [B,V], cache)."""
    b = token.shape[0]
    x = jnp.take(params["embed"], token, axis=0)[:, None, :]  # [B,1,D]
    pos = cache["len"]  # [B]
    for i, layer in enumerate(params["layers"]):
        hd = cfg.head_dim
        h = _rms_norm(x, layer["ln_attn"])
        q = _mm(h, layer["attn"]["wq"]).reshape(b, 1, cfg.n_heads, hd)
        k = _mm(h, layer["attn"]["wk"]).reshape(b, 1, cfg.n_kv_heads, hd)
        v = _mm(h, layer["attn"]["wv"]).reshape(b, 1, cfg.n_kv_heads, hd)
        q = _rope(q, pos[:, None], cfg.rope_theta)
        k = _rope(k, pos[:, None], cfg.rope_theta)
        # write this step's k/v at position `pos` (same for all batch rows in
        # the serving path; use per-row dynamic slice via one-hot scatter)
        # overwrite (not add) the slot at `pos` so a reused cache with stale
        # rows beyond the prompt can't corrupt this step's K/V
        slot = (jnp.arange(cfg.max_seq)[None, :] == pos[:, None])[:, :, None, None]
        cache["k"][i] = jnp.where(slot, k, cache["k"][i])
        cache["v"][i] = jnp.where(slot, v, cache["v"][i])
        # attention against the full static-shape cache, length-masked
        n_rep = cfg.n_heads // cfg.n_kv_heads
        kk = _repeat_kv(cache["k"][i], n_rep)
        vv = _repeat_kv(cache["v"][i], n_rep)
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, kk, preferred_element_type=jnp.float32
        ) * (hd ** -0.5)
        valid = jnp.arange(cfg.max_seq)[None, :] <= pos[:, None]
        s = jnp.where(valid[:, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vv.dtype), vv)
        out = _mm(attn.reshape(b, 1, cfg.n_heads * hd), layer["attn"]["wo"])
        x = x + out.astype(x.dtype)
        x, _ = _ffn_block(layer, x, cfg)
    x = _rms_norm(x, params["ln_f"])
    logits = _mm(x[:, 0], params["lm_head"]).astype(jnp.float32)
    cache["len"] = pos + 1
    return logits, cache


def paged_attention(q, pool_k, pool_v, tables, pos, cfg, block_size):
    """Attention of ``q`` ([B,T,H,hd], already roped) against a PAGED KV
    cache: ``pool_k``/``pool_v`` are one layer's block pools
    ([n_blocks+1, block_size, n_kv, hd], serve/lm/kv.KvBlockPool layout)
    and ``tables`` ([B, table_width] int32) maps each lane's logical
    block index to its physical pool block.  Length-masked at ``pos``
    ([B,T] logical query positions; keys at logical position j attend
    iff j <= pos), so trash-mapped rows are never read.

    This is the serving cache layout of serve/lm: the contiguous
    ``init_cache`` [B, max_seq, ...] layout pins max_seq rows per lane
    forever; the paged layout pools HBM across lanes and a lane holds
    only ceil((prompt+budget)/block_size) blocks.
    """
    b = q.shape[0]
    hd = cfg.head_dim
    n_rep = cfg.n_heads // cfg.n_kv_heads
    s_len = tables.shape[-1] * block_size
    kk = pool_k[tables].reshape(b, s_len, cfg.n_kv_heads, hd)
    vv = pool_v[tables].reshape(b, s_len, cfg.n_kv_heads, hd)
    kk = _repeat_kv(kk, n_rep)
    vv = _repeat_kv(vv, n_rep)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, kk, preferred_element_type=jnp.float32
    ) * (hd ** -0.5)
    valid = jnp.arange(s_len)[None, None, :] <= pos[:, :, None]  # [B,T,S]
    s = jnp.where(valid[:, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(vv.dtype), vv)


def lm_flops_per_token(cfg, context=0):
    """Model FLOPs one generated token costs (the MFU denominator for
    `tokens/sec` headlines, the LM analog of vision.cnn_flops_per_image).

    Counts 2 FLOPs per weight element in every matmul a token traverses
    (the PaLM 2N convention): attention projections, FFN (top_k experts
    for MoE configs — the routed math, not the dense formulation's
    all-experts execution), and the lm_head.  ``context`` > 0 adds the
    attention score/combine term (4 * n_heads * head_dim * context per
    layer), which depends on live sequence length; pass a typical
    context (e.g. prompt_len + max_tokens/2) for decode-phase MFU.
    """
    hd = cfg.head_dim
    attn_w = (
        cfg.d_model * cfg.n_heads * hd          # wq
        + 2 * cfg.d_model * cfg.n_kv_heads * hd  # wk, wv
        + cfg.n_heads * hd * cfg.d_model         # wo
    )
    ffn_active = 3 * cfg.d_model * cfg.d_ff
    if cfg.n_experts > 0:
        ffn_active *= cfg.top_k
        ffn_active += cfg.d_model * cfg.n_experts  # router
    per_layer = 2 * (attn_w + ffn_active)
    per_layer += 4 * cfg.n_heads * hd * int(context)  # scores + combine
    head = 2 * cfg.d_model * cfg.vocab_size
    return cfg.n_layers * per_layer + head


def _next_token_nll(logits, targets):
    """Mean next-token cross-entropy: logits [B,T,V] f32, targets [B,T]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.mean(-jnp.take_along_axis(logp, targets[..., None], axis=-1))


def loss_fn(params, tokens, cfg, mesh=None, attn_impl="plain"):
    """Next-token cross-entropy over tokens [B,T] (+ router aux for MoE)."""
    logits, aux = forward(
        params, tokens[:, :-1], cfg, mesh, attn_impl, with_aux=True
    )
    loss = _next_token_nll(logits, tokens[:, 1:])
    if cfg.n_experts > 0:
        loss = loss + cfg.router_aux_coef * aux
    return loss


def _make_adam_step(loss, learning_rate):
    """Shared Adam scaffolding: (loss(params, tokens) -> scalar) → jitted
    ``step(params, opt_state, tokens) -> (params, opt_state, loss)``."""
    import optax

    opt = optax.adam(learning_rate)

    def step(params, opt_state, tokens):
        value, grads = jax.value_and_grad(loss)(params, tokens)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, value

    return opt, jax.jit(step, donate_argnums=(0, 1))


def make_train_step(cfg, mesh=None, attn_impl="plain", learning_rate=1e-3):
    """Jitted Adam train step.  With a mesh, callers should device_put params
    per ``parallel.param_specs`` and the batch per ``parallel.batch_spec``;
    GSPMD propagates those shardings through grads and optimizer state."""
    return _make_adam_step(
        lambda params, tokens: loss_fn(params, tokens, cfg, mesh, attn_impl),
        learning_rate,
    )


def quantize_params(params):
    """Int8 weight-only quantization of the serving weights.

    Every 2D projection (attention, dense MLP, LM head) becomes a
    {"q": int8, "s": f32} pair consumed by the Pallas dequant-matmul
    (client_tpu.ops.quant) — halving weight HBM traffic on the
    bandwidth-bound decode path.  The embedding stays full-precision (it is
    a gather, not a matmul); MoE expert stacks keep their einsum path.
    This is a serving transform: quantized params are not trainable.
    """
    from client_tpu.ops.quant import quantize_int8

    def q_layer(layer):
        out = {
            "attn": {k: quantize_int8(w) for k, w in layer["attn"].items()},
            "ln_attn": layer["ln_attn"],
            "ln_mlp": layer["ln_mlp"],
        }
        if "mlp" in layer:
            out["mlp"] = {
                k: quantize_int8(w) for k, w in layer["mlp"].items()
            }
        if "moe" in layer:
            out["moe"] = layer["moe"]
        return out

    return {
        "embed": params["embed"],
        "layers": [q_layer(layer) for layer in params["layers"]],
        "ln_f": params["ln_f"],
        "lm_head": quantize_int8(params["lm_head"]),
    }


def stack_pipeline_params(params, n_stages):
    """Re-lay the per-layer list as pipeline stages (parallel.pipeline)."""
    from client_tpu.parallel.pipeline import stack_stage_params

    return {
        "embed": params["embed"],
        "stages": stack_stage_params(params["layers"], n_stages),
        "ln_f": params["ln_f"],
        "lm_head": params["lm_head"],
    }


def forward_pipelined(pparams, tokens, cfg, mesh, n_microbatches):
    """Full-sequence logits with the layer stack pipelined over ``pp``.

    Embedding and LM head run outside the pipeline region (replicated);
    each stage scans its local layer block over the incoming microbatch,
    whose batch dim shards over ``dp`` (parallel.pipeline batch_axis).
    """
    from client_tpu.parallel.pipeline import pipeline_apply

    b, t = tokens.shape
    x = jnp.take(pparams["embed"], tokens, axis=0)
    positions = jnp.arange(t)

    def stage_fn(stage_layers, h):
        def layer_step(hh, layer):
            hh, _ = _attention_block(layer, hh, cfg, positions, None, "plain")
            hh, _ = _ffn_block(layer, hh, cfg)
            return hh, None

        h, _ = lax.scan(layer_step, h, stage_layers)
        return h

    x = pipeline_apply(stage_fn, pparams["stages"], x, mesh, n_microbatches)
    x = _rms_norm(x, pparams["ln_f"])
    return _mm(x, pparams["lm_head"]).astype(jnp.float32)


def make_pipeline_train_step(cfg, mesh, n_microbatches, learning_rate=1e-3):
    """Jitted Adam train step over pipeline-stacked params: gradients flow
    back through the scan + ppermute schedule (reverse ppermute).  Pipeline
    composes with data parallelism (the microbatch shards over ``dp``
    inside the region — parallel.pipeline); stage weights are replicated
    over tp/ep within the region, and MoE router aux loss is not collected
    on this path."""

    def loss(pparams, tokens):
        logits = forward_pipelined(
            pparams, tokens[:, :-1], cfg, mesh, n_microbatches
        )
        return _next_token_nll(logits, tokens[:, 1:])

    return _make_adam_step(loss, learning_rate)


@functools.lru_cache(maxsize=8)
def _jitted_steps(cfg):
    """Per-config jitted prefill/decode (cfg is a frozen dataclass, hashable);
    caching here keeps repeated generate() calls on the same compiled programs."""
    return (
        jax.jit(functools.partial(prefill, cfg=cfg)),
        jax.jit(functools.partial(decode_step, cfg=cfg)),
    )


def generate(params, cfg, prompt, max_new_tokens, temperature=0.0, key=None,
             readback_depth=8, stop_tokens=()):
    """Greedy/sampled generation; yields one int token id at a time.

    Python-level loop over jitted prefill/decode steps — each yield maps to
    one decoupled KServe response in the streaming serving path.  Generation
    stops early if the KV cache fills (prompt_len + new tokens > cfg.max_seq)
    or a ``stop_tokens`` id is produced (the stop token is still yielded).

    The decode loop is pipelined: step i's token is selected on device and
    its D2H copy started with ``copy_to_host_async`` while decode step i+1
    is dispatched, keeping up to ``readback_depth`` readbacks in flight.
    Token selection stays on device, so the compute schedule — and the token
    stream — is identical to the serial order (``readback_depth=0``); only
    the host-side readback is deferred.  Over a high-RTT link this lifts the
    per-token cost from one full round trip (the blocking ``np.asarray`` in
    the serial loop) to ~RTT/depth, and on a local chip it overlaps readback
    with decode compute.

    Cost of the pipeline: a stop token is only *known* on host one readback
    latency after its decode step ran, so up to ``readback_depth`` decode
    steps past the stop get dispatched and discarded.  That waste is
    information-theoretic for any scheme that keeps the link busy (the host
    cannot know sooner), and bounded by depth; ``readback_depth=0`` restores
    the strict serial no-waste schedule.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    if prompt.ndim == 1:
        prompt = prompt[None, :]
    if temperature > 0.0 and key is None:
        key = jax.random.PRNGKey(0)
    # the cache slot for step i's token is prompt_len + i; the last usable
    # slot is max_seq - 1
    max_new_tokens = min(max_new_tokens, cfg.max_seq - prompt.shape[1])
    cache = init_cache(cfg, prompt.shape[0])
    prefill_fn, decode_fn = _jitted_steps(cfg)
    logits, cache = prefill_fn(params, prompt, cache=cache)
    depth = max(int(readback_depth), 0)
    stop = frozenset(int(t) for t in stop_tokens)
    pending = collections.deque()
    for i in range(max_new_tokens):
        if temperature > 0.0:
            key, sub = jax.random.split(key)
            token = jax.random.categorical(sub, logits / temperature, axis=-1)
        else:
            token = jnp.argmax(logits, axis=-1)
        token = token.astype(jnp.int32)
        if hasattr(token, "copy_to_host_async"):
            token.copy_to_host_async()
        pending.append(token)
        if i + 1 < max_new_tokens:
            logits, cache = decode_fn(params, token, cache=cache)
        while len(pending) > depth:
            t = int(np.asarray(pending.popleft())[0])
            yield t
            if t in stop:
                return  # stop dispatching; in-flight steps are discarded
    while pending:
        t = int(np.asarray(pending.popleft())[0])
        yield t
        if t in stop:
            return
