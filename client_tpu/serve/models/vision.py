"""Vision models for the in-process TPU server.

TPU-first design notes: forward passes are jitted once with static shapes so
XLA tiles the convolutions onto the MXU; parameters live on device in bfloat16
(compute) with float32 I/O at the protocol boundary. The CNN here is the
hermetic stand-in for the reference's densenet_onnx / inception example models
(BASELINE.md configs 1-2) — same tensor interface (NCHW image in, class scores
out), sized so a single v5e chip turns requests around in sub-millisecond time.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp

from client_tpu.serve.model_runtime import Model, TensorSpec

# ImageNet-ish class count so classification extension demos look real.
_NUM_CLASSES = 1000


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )


def _init_cnn_params(key, channels=(32, 64, 128, 256), in_ch=3, num_classes=_NUM_CLASSES):
    params = {"convs": [], "scales": []}
    k = key
    prev = in_ch
    for ch in channels:
        k, sub = jax.random.split(k)
        # python-float scale: numpy scalars are not weak-typed and would
        # promote the bfloat16 weights to float32
        params["convs"].append(
            jax.random.normal(sub, (ch, prev, 3, 3), jnp.bfloat16)
            * float(2.0 / np.sqrt(prev * 9))
        )
        params["scales"].append(jnp.ones((ch, 1, 1), jnp.bfloat16))
        prev = ch
    k, sub = jax.random.split(k)
    params["head"] = jax.random.normal(
        sub, (prev, num_classes), jnp.bfloat16
    ) * float(1.0 / np.sqrt(prev))
    return params


def _cnn_forward(params, x):
    # x: [N, 3, H, W] float32 -> scores [N, num_classes] float32
    h = x.astype(jnp.bfloat16)
    for w, s in zip(params["convs"], params["scales"]):
        h = _conv(h, w, stride=2)
        h = jax.nn.relu(h) * s
    h = jnp.mean(h, axis=(2, 3))  # global average pool
    return (h @ params["head"]).astype(jnp.float32)


def _conv_flops(out_ch, in_ch, kh, kw, out_h, out_w):
    # one MAC = 2 FLOPs; elementwise (relu/scale/add) is noise next to this
    return 2 * out_ch * in_ch * kh * kw * out_h * out_w


def cnn_flops_per_image(image_size=224, channels=(32, 64, 128, 256),
                        in_ch=3, num_classes=_NUM_CLASSES):
    """Analytic forward FLOPs for one image through the small CNN."""
    flops, hw, prev = 0, image_size, in_ch
    for ch in channels:
        hw = (hw + 1) // 2  # stride-2 SAME conv
        flops += _conv_flops(ch, prev, 3, 3, hw, hw)
        prev = ch
    return flops + 2 * prev * num_classes


class CnnClassifier:
    """Jitted CNN classifier servable; accepts any batch of 224x224 RGB."""

    def __init__(self, image_size=224, seed=0):
        self.image_size = image_size
        self.params = _init_cnn_params(jax.random.PRNGKey(seed))
        self._forward = jax.jit(_cnn_forward)

    def __call__(self, inputs, params, ctx):
        # jnp.asarray is a no-op for device-resident (TPU-shm) inputs; the
        # output stays a device array so shm-output responses never force a
        # D2H sync — the runtime materializes only for wire-tensor responses.
        x = jnp.asarray(inputs["INPUT0"])
        return {"OUTPUT0": self._forward(self.params, x)}


def cnn_classifier_model(
    name="cnn_classifier", image_size=224, max_batch_size=64, warmup=False
):
    """Servable Model wrapping CnnClassifier (densenet_onnx stand-in).

    Dynamic batching is on: concurrent wire requests fuse into one padded
    batched forward (one H2D, one MXU pass, one D2H per batch).
    """
    runner = CnnClassifier(image_size)
    labels = [f"class_{i}" for i in range(_NUM_CLASSES)]
    return Model(
        name,
        inputs=[TensorSpec("INPUT0", "FP32", [-1, 3, image_size, image_size])],
        outputs=[TensorSpec("OUTPUT0", "FP32", [-1, _NUM_CLASSES], labels=labels)],
        fn=runner,
        platform="jax",
        backend="jax",
        max_batch_size=max_batch_size,
        dynamic_batching=True,
        warmup=warmup,
        batch_device_inputs=True,
        fused_batching=True,
        max_fused_arity=16,
        flops_per_item=cnn_flops_per_image(image_size),
    )


# ---------------------------------------------------------------------------
# ResNet-50 (BASELINE.md config 3: perf_analyzer concurrency sweep on
# resnet50 with TPU HBM input tensors).  Real bottleneck residual blocks at
# the standard [3,4,6,3] depth — 4.09 GMACs = ~8.2 GFLOP per 224x224 image
# (the commonly cited "4.1 GFLOPs" counts MACs), so a
# throughput number on this model is a *compute* statement (MFU), not a
# protocol statement.  Inference-only: batch norm folds into the per-channel
# scales (s1..s3, stem_scale) at serving time.
# ---------------------------------------------------------------------------

# Single source of stage geometry: (mid_channels, n_blocks, first_stride)
# per stage.  _init_resnet_params, _resnet_forward and
# resnet50_flops_per_image all derive from this — change it in one place.
_RESNET50_STAGES = ((64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2))


def _he(key, shape, fan_in):
    return jax.random.normal(key, shape, jnp.bfloat16) * float(
        np.sqrt(2.0 / fan_in)
    )


def _init_resnet_params(key, in_ch=3, num_classes=_NUM_CLASSES,
                        stages=_RESNET50_STAGES):
    """Bottleneck ResNet-50 parameters: stem 7x7/2 + maxpool, then stages of
    (mid_ch, n_blocks, first_stride) bottlenecks (1x1 -> 3x3 -> 1x1 with a
    4x expansion), ending in a 1000-way linear head."""
    keys = iter(jax.random.split(key, 256))
    params = {
        "stem": _he(next(keys), (64, in_ch, 7, 7), in_ch * 49),
        "stem_scale": jnp.ones((64, 1, 1), jnp.bfloat16),
        "stages": [],
    }
    prev = 64
    for mid, n_blocks, first_stride in stages:
        out = mid * 4
        blocks = []
        for b in range(n_blocks):
            stride = first_stride if b == 0 else 1
            block = {
                "w1": _he(next(keys), (mid, prev, 1, 1), prev),
                "s1": jnp.ones((mid, 1, 1), jnp.bfloat16),
                "w2": _he(next(keys), (mid, mid, 3, 3), mid * 9),
                "s2": jnp.ones((mid, 1, 1), jnp.bfloat16),
                "w3": _he(next(keys), (out, mid, 1, 1), mid),
                "s3": jnp.ones((out, 1, 1), jnp.bfloat16),
            }
            if prev != out or stride != 1:
                block["proj"] = _he(next(keys), (out, prev, 1, 1), prev)
            blocks.append(block)
            prev = out
        params["stages"].append(blocks)
    params["head_w"] = _he(next(keys), (prev, num_classes), prev)
    params["head_b"] = jnp.zeros((num_classes,), jnp.bfloat16)
    return params


def _bottleneck(block, x, stride):
    h = jax.nn.relu(_conv(x, block["w1"]) * block["s1"])
    h = jax.nn.relu(_conv(h, block["w2"], stride=stride) * block["s2"])
    h = _conv(h, block["w3"]) * block["s3"]
    skip = x if "proj" not in block else _conv(x, block["proj"], stride=stride)
    return jax.nn.relu(h + skip)


def _resnet_features(params, x, stage_strides=None):
    """Backbone half: image -> pooled feature vector (the head applies in
    _resnet_head).  Split out so the vision *pipeline* can serve the
    backbone and the classification head as separate composing models with
    the feature tensor staying device-resident between them."""
    # strides are structural (static under jit tracing), not pytree leaves —
    # conv window_strides must be concrete.  Custom-`stages` params need a
    # matching stage_strides; the default follows _RESNET50_STAGES.
    strides = stage_strides or tuple(s for _, _, s in _RESNET50_STAGES)
    # x: [N, 3, H, W] float32 -> features [N, C] bfloat16
    h = x.astype(jnp.bfloat16)
    h = jax.nn.relu(_conv(h, params["stem"], stride=2) * params["stem_scale"])
    h = jax.lax.reduce_window(
        h, -jnp.inf, jax.lax.max,
        window_dimensions=(1, 1, 3, 3),
        window_strides=(1, 1, 2, 2),
        padding="SAME",
    )
    for si, blocks in enumerate(params["stages"]):
        for bi, block in enumerate(blocks):
            h = _bottleneck(block, h, strides[si] if bi == 0 else 1)
    return jnp.mean(h, axis=(2, 3))


def _resnet_head(params, h):
    """Classification head over pooled features -> float32 scores."""
    return (
        h.astype(jnp.bfloat16) @ params["head_w"] + params["head_b"]
    ).astype(jnp.float32)


def _resnet_forward(params, x, stage_strides=None):
    # x: [N, 3, H, W] float32 -> scores [N, num_classes] float32
    return _resnet_head(
        params, _resnet_features(params, x, stage_strides=stage_strides)
    )


def resnet50_flops_per_image(image_size=224, in_ch=3,
                             num_classes=_NUM_CLASSES,
                             stages=_RESNET50_STAGES):
    """Analytic forward FLOPs for one image, 2*MAC convention (convs +
    head): ~8.18e9 for 224px — i.e. 4.09 GMACs, matching torchvision's
    resnet50 profile.  MFU divides this by a peak quoted in FLOP/s, so the
    2*MAC convention is the consistent numerator."""
    def conv_out(hw, stride):
        return (hw + stride - 1) // stride

    flops = 0
    hw = conv_out(image_size, 2)  # stem 7x7/2
    flops += _conv_flops(64, in_ch, 7, 7, hw, hw)
    hw = conv_out(hw, 2)  # maxpool/2
    prev = 64
    for mid, n_blocks, first_stride in stages:
        out = mid * 4
        for b in range(n_blocks):
            stride = first_stride if b == 0 else 1
            # 1x1 reduce runs at the INPUT resolution, the 3x3 at the output
            flops += _conv_flops(mid, prev, 1, 1, hw, hw)
            hw_out = conv_out(hw, stride)
            flops += _conv_flops(mid, mid, 3, 3, hw_out, hw_out)
            flops += _conv_flops(out, mid, 1, 1, hw_out, hw_out)
            if prev != out or stride != 1:
                flops += _conv_flops(out, prev, 1, 1, hw_out, hw_out)
            prev = out
            hw = hw_out
    return flops + 2 * prev * num_classes


class ResNet50Classifier:
    """Jitted bottleneck ResNet-50 servable (~8.2 GFLOP / 224px image)."""

    def __init__(self, image_size=224, seed=0):
        self.image_size = image_size
        self.params = _init_resnet_params(jax.random.PRNGKey(seed))
        self._forward = jax.jit(_resnet_forward)

    def __call__(self, inputs, params, ctx):
        x = jnp.asarray(inputs["INPUT0"])
        return {"OUTPUT0": self._forward(self.params, x)}


# ---------------------------------------------------------------------------
# Vision pipeline (ensemble acceptance workload, serve/pipeline.py):
# preprocess -> resnet backbone -> classification postprocess, all jax-backed
# so every intermediate tensor stays in device HBM between steps — the DAG
# scheduler hands the jax.Array straight to the next composing model with
# zero host round-trips (asserted via ctpu_ensemble_host_hops_total).
# ---------------------------------------------------------------------------

# Tiny stage geometry for the hermetic default-model variant: ~0.4M params,
# compiles in well under a second on CPU.  Full-size callers pass
# stages=_RESNET50_STAGES.
_TINY_STAGES = ((16, 1, 1), (32, 1, 2))

_IMAGENET_MEAN = (0.485, 0.456, 0.406)
_IMAGENET_STD = (0.229, 0.224, 0.225)


def _preprocess_forward(x):
    """uint8 NHWC image batch -> normalized float32 NCHW pixels."""
    x = x.astype(jnp.float32) / 255.0
    x = jnp.transpose(x, (0, 3, 1, 2))
    mean = jnp.asarray(_IMAGENET_MEAN, jnp.float32).reshape(1, 3, 1, 1)
    std = jnp.asarray(_IMAGENET_STD, jnp.float32).reshape(1, 3, 1, 1)
    return (x - mean) / std


class _VisionPipelineRunners:
    """Shared lazy state behind the pipeline's composing models: one resnet
    parameter tree (backbone stages + classification head) initialized on
    first use so constructing the default model set stays cheap."""

    def __init__(self, image_size, stages, num_classes, seed=0):
        self.image_size = image_size
        self.stages = tuple(stages)
        self.num_classes = num_classes
        self.seed = seed
        self.feature_dim = self.stages[-1][0] * 4
        self._params = None  # init is idempotent; racing first calls agree
        self._pre = jax.jit(_preprocess_forward)
        strides = tuple(s for _, _, s in self.stages)
        self._features = jax.jit(
            functools.partial(_resnet_features, stage_strides=strides)
        )
        self._head = jax.jit(_resnet_head)

    def _ensure(self):
        params = self._params
        if params is None:
            params = _init_resnet_params(
                jax.random.PRNGKey(self.seed),
                num_classes=self.num_classes,
                stages=self.stages,
            )
            self._params = params
        return params

    def preprocess(self, inputs, params, ctx):
        return {"PIXELS": self._pre(jnp.asarray(inputs["IMAGE"]))}

    def backbone(self, inputs, params, ctx):
        # jnp.asarray is a no-op for the device-resident PIXELS handoff;
        # the float32 cast honors the FEATURES spec and stays on device
        return {
            "FEATURES": self._features(
                self._ensure(), jnp.asarray(inputs["PIXELS"])
            ).astype(jnp.float32)
        }

    def postprocess(self, inputs, params, ctx):
        scores = self._head(self._ensure(), jnp.asarray(inputs["FEATURES"]))
        return {"SCORES": jax.nn.softmax(scores, axis=-1)}


def vision_pipeline_models(
    image_size=32,
    stages=_TINY_STAGES,
    num_classes=16,
    max_batch_size=32,
    warmup=False,
    prefix="vision",
):
    """The vision-pipeline model family: three jax-backed composing models
    plus the ensemble wiring them into a DAG.

    - ``{prefix}_preprocess``: UINT8 NHWC image -> normalized FP32 NCHW
      (direct dispatch: trivially cheap, and its jitted output is already a
      device array, which puts the backbone step on the batcher's device
      path).
    - ``{prefix}_backbone``: resnet features, dynamic batching + fused
      device groups — concurrent pipeline requests fuse into real MXU
      batches mid-DAG.
    - ``{prefix}_postprocess``: classification head + softmax, labels
      attached for the classification extension.
    - ``{prefix}_pipeline``: the ensemble (IMAGE -> SCORES).

    Defaults are the hermetic tiny variant served by the builtin model set;
    bench passes ``image_size=224, stages=_RESNET50_STAGES,
    num_classes=1000`` for the full resnet50-backed pipeline.
    """
    runners = _VisionPipelineRunners(image_size, stages, num_classes)
    labels = [f"class_{i}" for i in range(num_classes)]
    feat = runners.feature_dim
    preprocess = Model(
        f"{prefix}_preprocess",
        inputs=[TensorSpec("IMAGE", "UINT8", [-1, image_size, image_size, 3])],
        outputs=[TensorSpec("PIXELS", "FP32", [-1, 3, image_size, image_size])],
        fn=runners.preprocess,
        platform="jax",
        backend="jax",
        max_batch_size=max_batch_size,
    )
    backbone = Model(
        f"{prefix}_backbone",
        inputs=[TensorSpec("PIXELS", "FP32", [-1, 3, image_size, image_size])],
        outputs=[TensorSpec("FEATURES", "FP32", [-1, feat])],
        fn=runners.backbone,
        platform="jax",
        backend="jax",
        max_batch_size=max_batch_size,
        dynamic_batching=True,
        batch_device_inputs=True,
        warmup=warmup,
    )
    postprocess = Model(
        f"{prefix}_postprocess",
        inputs=[TensorSpec("FEATURES", "FP32", [-1, feat])],
        outputs=[TensorSpec("SCORES", "FP32", [-1, num_classes], labels=labels)],
        fn=runners.postprocess,
        platform="jax",
        backend="jax",
        max_batch_size=max_batch_size,
    )
    pipeline = Model(
        f"{prefix}_pipeline",
        inputs=[TensorSpec("IMAGE", "UINT8", [-1, image_size, image_size, 3])],
        outputs=[TensorSpec("SCORES", "FP32", [-1, num_classes], labels=labels)],
        fn=None,
        platform="ensemble",
        ensemble_steps=[
            {
                "model_name": f"{prefix}_preprocess",
                "input_map": {"IMAGE": "IMAGE"},
                "output_map": {"PIXELS": "pixels"},
            },
            {
                "model_name": f"{prefix}_backbone",
                "input_map": {"PIXELS": "pixels"},
                "output_map": {"FEATURES": "features"},
            },
            {
                "model_name": f"{prefix}_postprocess",
                "input_map": {"FEATURES": "features"},
                "output_map": {"SCORES": "SCORES"},
            },
        ],
    )
    return [preprocess, backbone, postprocess, pipeline]


def resnet50_model(
    name="resnet50", image_size=224, max_batch_size=64, warmup=False
):
    """Servable ResNet-50 (BASELINE.md config 3's model, rebuilt natively in
    JAX rather than loaded from ONNX).  Reference analog: the resnet50
    concurrency sweep perf_analyzer README documents; cited in SURVEY §6."""
    runner = ResNet50Classifier(image_size)
    labels = [f"class_{i}" for i in range(_NUM_CLASSES)]
    return Model(
        name,
        inputs=[TensorSpec("INPUT0", "FP32", [-1, 3, image_size, image_size])],
        outputs=[TensorSpec("OUTPUT0", "FP32", [-1, _NUM_CLASSES], labels=labels)],
        fn=runner,
        platform="jax",
        backend="jax",
        max_batch_size=max_batch_size,
        dynamic_batching=True,
        warmup=warmup,
        batch_device_inputs=True,
        fused_batching=True,
        max_fused_arity=16,
        flops_per_item=resnet50_flops_per_image(image_size),
    )
