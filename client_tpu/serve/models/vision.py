"""Vision models for the in-process TPU server.

TPU-first design notes: forward passes are jitted once with static shapes so
XLA tiles the convolutions onto the MXU; parameters live on device in bfloat16
(compute) with float32 I/O at the protocol boundary. The CNN here is the
hermetic stand-in for the reference's densenet_onnx / inception example models
(BASELINE.md configs 1-2) — same tensor interface (NCHW image in, class scores
out), sized so a single v5e chip turns requests around in sub-millisecond time.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp

from client_tpu.serve.model_runtime import Model, TensorSpec

# ImageNet-ish class count so classification extension demos look real.
_NUM_CLASSES = 1000


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )


def _init_cnn_params(key, channels=(32, 64, 128, 256), in_ch=3, num_classes=_NUM_CLASSES):
    params = {"convs": [], "scales": []}
    k = key
    prev = in_ch
    for ch in channels:
        k, sub = jax.random.split(k)
        # python-float scale: numpy scalars are not weak-typed and would
        # promote the bfloat16 weights to float32
        params["convs"].append(
            jax.random.normal(sub, (ch, prev, 3, 3), jnp.bfloat16)
            * float(2.0 / np.sqrt(prev * 9))
        )
        params["scales"].append(jnp.ones((ch, 1, 1), jnp.bfloat16))
        prev = ch
    k, sub = jax.random.split(k)
    params["head"] = jax.random.normal(
        sub, (prev, num_classes), jnp.bfloat16
    ) * float(1.0 / np.sqrt(prev))
    return params


def _cnn_forward(params, x):
    # x: [N, 3, H, W] float32 -> scores [N, num_classes] float32
    h = x.astype(jnp.bfloat16)
    for w, s in zip(params["convs"], params["scales"]):
        h = _conv(h, w, stride=2)
        h = jax.nn.relu(h) * s
    h = jnp.mean(h, axis=(2, 3))  # global average pool
    return (h @ params["head"]).astype(jnp.float32)


class CnnClassifier:
    """Jitted CNN classifier servable; accepts any batch of 224x224 RGB."""

    def __init__(self, image_size=224, seed=0):
        self.image_size = image_size
        self.params = _init_cnn_params(jax.random.PRNGKey(seed))
        self._forward = jax.jit(_cnn_forward)

    def __call__(self, inputs, params, ctx):
        # jnp.asarray is a no-op for device-resident (TPU-shm) inputs; the
        # output stays a device array so shm-output responses never force a
        # D2H sync — the runtime materializes only for wire-tensor responses.
        x = jnp.asarray(inputs["INPUT0"])
        return {"OUTPUT0": self._forward(self.params, x)}


def cnn_classifier_model(
    name="cnn_classifier", image_size=224, max_batch_size=64, warmup=False
):
    """Servable Model wrapping CnnClassifier (densenet_onnx stand-in).

    Dynamic batching is on: concurrent wire requests fuse into one padded
    batched forward (one H2D, one MXU pass, one D2H per batch).
    """
    runner = CnnClassifier(image_size)
    labels = [f"class_{i}" for i in range(_NUM_CLASSES)]
    return Model(
        name,
        inputs=[TensorSpec("INPUT0", "FP32", [-1, 3, image_size, image_size])],
        outputs=[TensorSpec("OUTPUT0", "FP32", [-1, _NUM_CLASSES], labels=labels)],
        fn=runner,
        platform="jax",
        backend="jax",
        max_batch_size=max_batch_size,
        dynamic_batching=True,
        warmup=warmup,
        batch_device_inputs=True,
        fused_batching=True,
        max_fused_arity=16,
    )
