"""JAX/TPU model zoo for the in-process server (flagship models).

``model_sets("builtin,jax,resnet,language")`` is the single set-name resolver
used by the serve and perf CLIs; ``jax_models()`` is the small-CNN vision set
used by bench.py, ``resnet_models()`` the resnet50 of BASELINE config 3, and
``language_models()`` the tokenizer→streaming-LM stack of BASELINE config 5.
"""

from client_tpu.utils import InferenceServerException


def jax_models():
    from client_tpu.serve.models.vision import cnn_classifier_model
    return [cnn_classifier_model()]


def resnet_models():
    from client_tpu.serve.models.vision import resnet50_model
    return [resnet50_model()]


def language_models():
    from client_tpu.serve.models.language import language_models as _lm
    return _lm()


def model_sets(names):
    """Resolve a comma-separated set list (builtin,jax,resnet,language)."""
    from client_tpu.serve.builtins import default_models

    loaders = {
        "builtin": default_models,
        "jax": jax_models,
        "resnet": resnet_models,
        "language": language_models,
    }
    models = []
    for name in names.split(","):
        name = name.strip()
        if not name:
            continue
        if name not in loaders:
            raise InferenceServerException(
                f"unknown model set '{name}' (available: "
                f"{', '.join(sorted(loaders))})"
            )
        models.extend(loaders[name]())
    return models
