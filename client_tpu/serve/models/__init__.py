"""JAX/TPU model zoo for the in-process server (flagship models).

Populated by client_tpu.serve.models.* ; ``jax_models()`` returns the servable
set used by bench.py and the TPU example configs.
"""


def jax_models():
    from client_tpu.serve.models.vision import cnn_classifier_model
    return [cnn_classifier_model()]
