"""JAX/TPU model zoo for the in-process server (flagship models).

``model_sets("builtin,jax,resnet,language,pipeline")`` is the single set-name
resolver used by the serve and perf CLIs; ``jax_models()`` is the small-CNN
vision set used by bench.py, ``resnet_models()`` the resnet50 of BASELINE
config 3, ``language_models()`` the tokenizer→streaming-LM stack of BASELINE
config 5, and ``pipeline_models()`` the full-size vision ensemble DAG
(preprocess → resnet50 backbone → classification postprocess).
"""

from client_tpu.utils import InferenceServerException


def jax_models():
    from client_tpu.serve.models.vision import cnn_classifier_model
    return [cnn_classifier_model()]


def resnet_models():
    from client_tpu.serve.models.vision import resnet50_model
    return [resnet50_model()]


def language_models(speculative=None):
    from client_tpu.serve.models.language import language_models as _lm
    return _lm(speculative=speculative)


def pipeline_models(warmup=False):
    """Full-size vision pipeline (224px resnet50 backbone): the ensemble
    DAG acceptance workload at serving scale."""
    from client_tpu.serve.models.vision import (
        _RESNET50_STAGES,
        vision_pipeline_models,
    )

    return vision_pipeline_models(
        image_size=224, stages=_RESNET50_STAGES, num_classes=1000,
        max_batch_size=64, warmup=warmup,
    )


def model_sets(names, speculative=None):
    """Resolve a comma-separated set list
    (builtin,jax,resnet,language,pipeline).  ``speculative`` (a
    SpecConfig-shaped dict) applies to the ``language`` set's batched
    engines only — perf's ``--speculative K --drafter ngram`` threads
    through here."""
    from client_tpu.serve.builtins import default_models

    loaders = {
        "builtin": default_models,
        "jax": jax_models,
        "resnet": resnet_models,
        "language": lambda: language_models(speculative=speculative),
        "pipeline": pipeline_models,
    }
    models = []
    for name in names.split(","):
        name = name.strip()
        if not name:
            continue
        if name not in loaders:
            raise InferenceServerException(
                f"unknown model set '{name}' (available: "
                f"{', '.join(sorted(loaders))})"
            )
        models.extend(loaders[name]())
    return models
