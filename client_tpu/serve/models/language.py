"""Language-model serving stack: tokenizer + streaming decoder LM.

The BASELINE.md config-5 shape ("Llama-3 ensemble: tokenizer → LLM streaming
infer"): a byte-level tokenizer model, a decoupled LM that streams one
response per generated token (the KServe decoupled/LLM pattern the reference
exercises via Triton's repeat/decoupled models), and an end-to-end text
ensemble that chains them server-side.

The LM is the flagship transformer (models/transformer.py) at a small
byte-vocab configuration so it runs hermetically; swap ``TransformerConfig``
for a full-size model on real deployments.  Token streaming maps one yielded
dict to one decoupled KServe response, which the gRPC frontend delivers over
ModelStreamInfer.
"""

import numpy as np

import jax

from client_tpu.serve.model_runtime import Model, TensorSpec
from client_tpu.serve.models import transformer as tfm
from client_tpu.utils import InferenceServerException

# byte-level vocab: 256 bytes + BOS + EOS
_BOS = 256
_EOS = 257
_VOCAB = 258

# The hermetic serving configuration (swap for a full-size model on real
# deployments).  Module-level so harnesses (bench.py's lm_mfu_pct) can
# compute tfm.lm_flops_per_token without instantiating a runner's params.
DEFAULT_LM_CONFIG = tfm.TransformerConfig(
    vocab_size=_VOCAB,
    d_model=256,
    n_layers=4,
    n_heads=8,
    n_kv_heads=4,
    d_ff=768,
    max_seq=512,
)


def encode_text(text):
    """Byte-level tokenize: BOS + utf-8 bytes."""
    if isinstance(text, str):
        text = text.encode("utf-8")
    return np.array([_BOS] + list(text), dtype=np.int32)


def decode_tokens(tokens):
    """Tokens -> utf-8 text (BOS/EOS stripped, lone surrogates replaced)."""
    return bytes(t for t in tokens if 0 <= t < 256).decode(
        "utf-8", errors="replace"
    )


def tokenizer_model(name="tokenizer"):
    """BYTES text -> INT32 token ids (ragged rows padded with EOS)."""

    def fn(inputs, params, ctx):
        texts = np.atleast_1d(inputs["TEXT"]).reshape(-1)
        rows = [encode_text(t) for t in texts]
        width = max(len(r) for r in rows)
        out = np.full((len(rows), width), _EOS, dtype=np.int32)
        for i, r in enumerate(rows):
            out[i, : len(r)] = r
        lengths = np.array([len(r) for r in rows], dtype=np.int32)
        return {"TOKENS": out, "LENGTHS": lengths}

    return Model(
        name,
        inputs=[TensorSpec("TEXT", "BYTES", [-1])],
        outputs=[
            TensorSpec("TOKENS", "INT32", [-1, -1]),
            TensorSpec("LENGTHS", "INT32", [-1]),
        ],
        fn=fn,
        platform="python",
    )


def detokenizer_model(name="detokenizer"):
    """INT32 token ids -> BYTES text."""

    def fn(inputs, params, ctx):
        tokens = np.atleast_2d(inputs["TOKENS"])
        texts = [decode_tokens(row).encode("utf-8") for row in tokens]
        return {"TEXT": np.array(texts, dtype=np.object_)}

    return Model(
        name,
        inputs=[TensorSpec("TOKENS", "INT32", [-1, -1])],
        outputs=[TensorSpec("TEXT", "BYTES", [-1])],
        fn=fn,
        platform="python",
    )


class _LmRunner:
    """Owns the transformer params + jitted decode programs."""

    def __init__(self, cfg=None, seed=0, quantize=False, params=None):
        self.cfg = cfg or DEFAULT_LM_CONFIG
        if params is None:
            params = tfm.init_params(jax.random.PRNGKey(seed), self.cfg)
        self.params = params
        if quantize:
            # int8 weight-only serving (client_tpu.ops.quant): ~2x weight
            # capacity per chip, same decode programs via the _mm dispatch
            self.params = tfm.quantize_params(self.params)

    def check_prompt(self, n_prompt_tokens):
        """Reject prompts the KV cache cannot hold with a clear 400 instead
        of an opaque shape error out of the jitted prefill (r1 advisor)."""
        if n_prompt_tokens >= self.cfg.max_seq:
            raise InferenceServerException(
                f"prompt of {n_prompt_tokens} tokens exceeds the model's "
                f"maximum context of {self.cfg.max_seq} (need at least one "
                "free slot to generate)",
                status="400",
            )
        if n_prompt_tokens == 0:
            raise InferenceServerException("empty prompt", status="400")

    def stream(self, tokens, max_tokens, temperature=0.0, seed=0,
               top_k=0, tenant=""):
        self.check_prompt(int(np.asarray(tokens).reshape(-1).shape[0]))
        if top_k and int(top_k) > 0:
            raise InferenceServerException(
                "top_k sampling needs the continuous-batching engine "
                "(lm_streaming_batched); this model samples the full "
                "distribution", status="400",
            )
        key = jax.random.PRNGKey(seed) if temperature > 0 else None
        for tok in tfm.generate(
            self.params, self.cfg, tokens, max_tokens,
            temperature=temperature, key=key, stop_tokens=(_EOS,),
        ):
            yield tok
            if tok == _EOS:
                return


def lm_streaming_model(name="lm_streaming", runner=None):
    """Decoupled LM: one KServe response per generated token.

    Inputs: TOKENS (prompt ids), MAX_TOKENS; optional request parameters
    ``temperature`` and ``seed``.  Each response carries the token id and its
    decoded text piece — the Triton LLM-streaming response shape.
    """
    runner = runner or _LmRunner()

    def fn(inputs, params, ctx):
        tokens = np.asarray(inputs["TOKENS"]).reshape(-1).astype(np.int32)
        max_tokens = int(np.asarray(inputs["MAX_TOKENS"]).flatten()[0])
        temperature = float(params.get("temperature", 0.0) or 0.0)
        seed = int(params.get("seed", 0) or 0)
        # top_k rides as a request parameter; __tenant__ is the RESERVED
        # caller identity the engine stamps from x-tenant-id (decoupled
        # models bypass the front door, so lane quotas are enforced at
        # decode-lane admission inside the LM engine instead)
        top_k = int(params.get("top_k", 0) or 0)
        tenant = str(params.get("__tenant__", "") or "")
        for tok in runner.stream(tokens, max_tokens, temperature, seed,
                                 top_k=top_k, tenant=tenant):
            piece = decode_tokens([tok]).encode("utf-8")
            yield {
                "TOKEN": np.array([tok], dtype=np.int32),
                "TEXT": np.array([piece], dtype=np.object_),
            }

    return Model(
        name,
        inputs=[
            TensorSpec("TOKENS", "INT32", [-1]),
            TensorSpec("MAX_TOKENS", "INT32", [1]),
        ],
        outputs=[
            TensorSpec("TOKEN", "INT32", [1]),
            TensorSpec("TEXT", "BYTES", [1]),
        ],
        fn=fn,
        decoupled=True,
    )


def lm_streaming_batched_model(name="lm_streaming_batched", runner=None,
                               max_slots=8, response_cache=None,
                               speculative=None, **engine_kwargs):
    """Decoupled LM with CONTINUOUS BATCHING: concurrent streams share one
    batched decode tick per token step (serve/lm: paged KV cache, bucketed
    + chunked prefill, KV prefix caching, lane autoscaling), so aggregate
    tokens/sec scales with active streams instead of serializing whole
    per-request decode programs.  Per-request ``temperature``/``top_k``/
    ``seed`` sample inside the jitted tick via per-lane RNG keys; same
    request/response surface as lm_streaming — the model IS
    lm_streaming_model with the batched runner behind it.

    ``response_cache`` is the per-model cache-hint config block; its
    ``prefix_cache`` sub-block carries the KV prefix-cache knobs this
    model's engine honors: ``{"prefix_cache": {"enable": bool,
    "min_prefix_blocks": int}}`` (the response-cache half is moot here —
    decoupled models never hit the unary response cache — but the block
    rides the model config so operators read one policy surface).

    ``speculative`` turns on speculative decoding for this model's
    engine (off by default): ``{"k": 4, "drafter": "ngram", ...}`` —
    see serve/lm/spec.py:SpecConfig for the full knob set.  Greedy
    streams keep byte-exact output; temperature streams stay
    distribution-exact via rejection sampling."""
    from client_tpu.serve.models.continuous import BatchedLmRunner

    prefix_knobs = dict((response_cache or {}).get("prefix_cache") or {})
    if "enable" in prefix_knobs:
        engine_kwargs.setdefault("prefix_cache",
                                 bool(prefix_knobs["enable"]))
    if "min_prefix_blocks" in prefix_knobs:
        engine_kwargs.setdefault("min_prefix_blocks",
                                 int(prefix_knobs["min_prefix_blocks"]))
    if speculative is not None:
        engine_kwargs.setdefault("speculative", speculative)
    base = runner or _LmRunner()
    batched = BatchedLmRunner(
        base.params, base.cfg, max_slots=max_slots, eos_id=_EOS,
        check_prompt=base.check_prompt, **engine_kwargs,
    )
    model = lm_streaming_model(name=name, runner=batched)
    model.response_cache = dict(response_cache or {}) or None
    # the scheduler's thread + paged KV pool release with the engine
    model.closer = batched.scheduler.close

    def bind(engine):
        """Late-bind the owning InferenceEngine's observability + QoS
        (add_model calls this): lane/KV/prefix gauges land in the
        server's /metrics registry, per-tick spans ride its tracer, and
        tenant decode-lane quotas + preemption priority classes come
        from the front door's TenantQoS."""
        sched = batched.scheduler
        sched.set_registry(engine.metrics)
        sched.tracer = engine.tracer
        sched.flight = getattr(engine, "flight", None)
        if getattr(engine, "prof", None) is not None:
            # the scheduler's per-tick profiler joins the server's so
            # /v2/debug/prof and flight dumps cover the LM engine
            engine.prof.adopt(sched.prof)
        if engine.qos is not None:
            sched.tenant_lane_share = engine.qos.lane_share
            sched.tenant_priority = engine.qos.priority
        if getattr(engine, "fleet", None) is not None:
            # cross-replica prefix tier: submit-side peer lookups,
            # prefill-completion exports, parked-stream migration
            sched.set_fleet(engine.fleet)

    model.binder = bind
    return model


def text_ensemble_model(name="text_generator", runner=None):
    """End-to-end ensemble: BYTES prompt -> streamed BYTES pieces.

    Chains tokenizer -> LM server-side, the ensemble pattern of BASELINE
    config 5 (client sends text, receives a token stream)."""
    runner = runner or _LmRunner()

    def fn(inputs, params, ctx):
        text = np.asarray(inputs["PROMPT"]).reshape(-1)[0]
        max_tokens = int(np.asarray(inputs["MAX_TOKENS"]).flatten()[0])
        temperature = float(params.get("temperature", 0.0) or 0.0)
        seed = int(params.get("seed", 0) or 0)
        tokens = encode_text(text)
        for tok in runner.stream(tokens, max_tokens, temperature, seed):
            piece = decode_tokens([tok]).encode("utf-8")
            yield {"TEXT": np.array([piece], dtype=np.object_)}

    return Model(
        name,
        inputs=[
            TensorSpec("PROMPT", "BYTES", [1]),
            TensorSpec("MAX_TOKENS", "INT32", [1]),
        ],
        outputs=[TensorSpec("TEXT", "BYTES", [1])],
        fn=fn,
        platform="ensemble",
        decoupled=True,
    )


def language_models(shared_runner=True, speculative=None,
                    int8_batched=None):
    """The full language set; one shared LM runner keeps params/compile warm.

    ``lm_streaming_int8`` serves the same architecture from int8-quantized
    weights (weight-only; client_tpu.ops.quant).  On TPU it serves through
    the continuous-batching engine exactly like the float model (the int8
    dequant-matmul is the same ``_mm`` dispatch the engine's jitted
    tick/prefill/verify programs already route through); off-TPU the
    Pallas kernel only runs in interpret mode — hundreds of ms per
    dispatch, which would bury the engine's scheduling wins — so the
    serial path stays the default there.  ``int8_batched`` overrides the
    auto-detection either way.

    ``speculative`` enables speculative decoding on the batched engines
    (see :func:`lm_streaming_batched_model`); the perf CLI's
    ``--speculative K --drafter ngram`` lands here.
    """
    runner = _LmRunner() if shared_runner else None
    # the int8 runner quantizes the SHARED weights (no second param init)
    int8_runner = _LmRunner(
        cfg=runner.cfg if runner else None,
        params=runner.params if runner else None,
        quantize=True,
    )
    if int8_batched is None:
        int8_batched = jax.default_backend() == "tpu"
    int8_model = (
        lm_streaming_batched_model(
            name="lm_streaming_int8", runner=int8_runner,
            speculative=speculative,
        )
        if int8_batched else
        lm_streaming_model(name="lm_streaming_int8", runner=int8_runner)
    )
    return [
        tokenizer_model(),
        detokenizer_model(),
        lm_streaming_model(runner=runner),
        int8_model,
        lm_streaming_batched_model(runner=runner,
                                   speculative=speculative),
        text_ensemble_model(runner=runner),
    ]
