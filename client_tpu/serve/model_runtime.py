"""In-process KServe-v2 model runtime.

This is the server-side half of the framework: a model repository + inference
engine that the HTTP and gRPC frontends (http_server.py / grpc_server.py) share.
It serves two roles:

1. Hermetic test double — the fake-server role SURVEY.md §4 calls for (the
   reference has no in-repo server; its tests need external infra).
2. Real TPU serving path — models whose ``fn`` is a jitted JAX callable run on
   the TPU chip, which is what bench.py measures end-to-end.

Request execution semantics (shared-memory resolution, classification
extension, statistics accounting) follow the KServe-v2 spec the reference
clients target.
"""

import base64
import json
import mmap
import os
import threading
import time

import numpy as np

from client_tpu.utils import (
    InferenceServerException,
    from_wire_bytes,
    to_wire_bytes,
)
from client_tpu._infer_types import _np_from_json_data
from client_tpu.serve._completion import CompletionObserver
from client_tpu.serve.metrics import (
    BATCH_BUCKETS,
    FLEET_HELP,
    Histogram,
    Registry,
)
from client_tpu.serve.flight import FlightRecorder
from client_tpu.serve.prof import PhaseProfiler
from client_tpu.serve.tracing import (
    TRACE_SETTING_DEFAULTS,
    Tracer,
    current_trace,
    normalize_trace_settings,
    push_trace,
)

SERVER_NAME = "client_tpu.serve"
SERVER_VERSION = "0.1.0"
SERVER_EXTENSIONS = [
    "classification",
    "sequence",
    "model_repository",
    "model_repository(unload_dependents)",
    "schedule_policy",
    "model_configuration",
    "system_shared_memory",
    "tpu_shared_memory",
    "binary_tensor_data",
    "parameters",
    "statistics",
    "trace",
    "logging",
]


class TensorSpec:
    """Metadata for one model input/output tensor."""

    def __init__(self, name, datatype, dims, labels=None, optional=False):
        self.name = name
        self.datatype = datatype
        self.dims = list(dims)
        self.labels = labels or []
        self.optional = optional

    def metadata(self):
        return {"name": self.name, "datatype": self.datatype, "shape": self.dims}


def _seq_encode(value):
    """JSON-safe encoding of one sequence-state value (numpy arrays and
    scalars become tagged base64/item dicts; containers recurse; anything
    else must already be JSON-serializable — the fleet tier ships these
    snapshots as JSON frames)."""
    if isinstance(value, np.ndarray):
        return {
            "__nd__": [
                str(value.dtype),
                list(value.shape),
                base64.b64encode(
                    np.ascontiguousarray(value).tobytes()
                ).decode("ascii"),
            ]
        }
    if isinstance(value, (np.integer, np.floating, np.bool_)):
        return {"__np__": [str(value.dtype), value.item()]}
    if isinstance(value, bytes):
        return {"__b__": base64.b64encode(value).decode("ascii")}
    if isinstance(value, dict):
        return {k: _seq_encode(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_seq_encode(v) for v in value]
    return value


def _seq_decode(value):
    if isinstance(value, dict):
        if "__nd__" in value and len(value) == 1:
            dtype, shape, data = value["__nd__"]
            return np.frombuffer(
                base64.b64decode(data), dtype=np.dtype(dtype)
            ).reshape(shape).copy()
        if "__np__" in value and len(value) == 1:
            dtype, item = value["__np__"]
            return np.dtype(dtype).type(item)
        if "__b__" in value and len(value) == 1:
            return base64.b64decode(value["__b__"])
        return {k: _seq_decode(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_seq_decode(v) for v in value]
    return value


class SequenceContext:
    """Per-sequence state handed to stateful model functions.

    ``step`` is the monotonic applied-step counter: the engine bumps it
    once per successfully executed request of the sequence, and requests
    that declare their own ``sequence_step`` parameter are replayed
    idempotently when the counter already covers them (the retained
    ``last_response`` rendering answers the duplicate without
    re-applying).  ``export()``/``restore()`` are the versioned snapshot
    pair the fleet tier replicates: versions order by ``(epoch, step)``
    and a snapshot that does not beat the stored version is stale and
    rejected, so replication can never move a sequence backwards.
    ``epoch`` stamps the sequence INCARNATION (wall clock at creation;
    restores keep the original): a client that restarts a sequence id
    with ``sequence_start`` mints a new, higher epoch, so the fresh
    incarnation's step-1 snapshot overwrites the dead incarnation's
    higher-step leftovers on every peer instead of being rejected as
    stale.
    """

    def __init__(self, sequence_id, durable=False):
        self.sequence_id = sequence_id
        self.state = {}
        self.step = 0
        # incarnation stamp, NOT a deadline: wall time so a restart on
        # any replica orders after the previous incarnation
        self.epoch = time.time()
        self.durable = bool(durable)
        # (step, id-less response dict, blobs) of the last applied step —
        # what an idempotent duplicate replay returns
        self.last_response = None
        self.last_used = time.monotonic()
        # traceparent of the last committed step's request trace: rides
        # the replicated snapshot so a survivor resuming this sequence
        # can CONTINUE the dead replica's trace id (serve/tracing.py
        # resume_span) — a SIGKILL failover reads as one trace
        self.trace_ctx = None
        # set when a quorum-mode publish could not reach its peer-ack
        # floor: the step is applied locally but was answered 503, and
        # the idempotent replay path must re-attempt the publish before
        # releasing the retained rendering (a 200 always implies the
        # snapshot reached quorum)
        self.quorum_deficit = False

    def export(self):
        """Serializable snapshot: JSON-safe through the fleet tier's
        frame transport (numpy state base64-tagged)."""
        last = None
        if self.last_response is not None:
            step, response, blobs = self.last_response
            last = {
                "step": int(step),
                "response": response,
                "blobs": [
                    base64.b64encode(bytes(b)).decode("ascii")
                    for b in blobs
                ],
            }
        return {
            "sequence_id": self.sequence_id,
            "step": int(self.step),
            "epoch": float(self.epoch),
            "durable": self.durable,
            "state": _seq_encode(self.state),
            "last_response": last,
            "traceparent": self.trace_ctx,
        }

    @classmethod
    def restore(cls, snapshot):
        """Rebuild a context from an exported snapshot (the survivor-side
        half of sequence migration)."""
        ctx = cls(snapshot["sequence_id"],
                  durable=snapshot.get("durable", False))
        ctx.step = int(snapshot.get("step", 0))
        ctx.epoch = float(snapshot.get("epoch", 0.0))
        ctx.state = _seq_decode(snapshot.get("state") or {})
        ctx.trace_ctx = snapshot.get("traceparent")
        last = snapshot.get("last_response")
        if last is not None:
            ctx.last_response = (
                int(last["step"]),
                last["response"],
                [base64.b64decode(b) for b in last.get("blobs") or ()],
            )
        return ctx


class Model:
    """A servable model: tensor specs + a python/JAX callable.

    ``fn(inputs, parameters, context)`` takes a dict of numpy arrays and
    returns a dict of numpy arrays — or, for ``decoupled=True`` models, an
    iterator of such dicts (the LLM token-streaming shape).  ``context`` is a
    SequenceContext when the request carries a sequence id, else None.
    """

    def __init__(
        self,
        name,
        inputs,
        outputs,
        fn,
        platform="python",
        backend="python",
        versions=("1",),
        max_batch_size=0,
        decoupled=False,
        stateful=False,
        dynamic_batching=False,
        max_queue_delay_us=3000,
        warmup=False,
        batch_device_inputs=False,
        fused_batching=False,
        max_fused_arity=8,
        max_queue_depth=None,
        ensemble_steps=None,
        flops_per_item=None,
        response_cache=None,
    ):
        self.name = name
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.fn = fn
        self.platform = platform
        self.backend = backend
        self.versions = [str(v) for v in versions]
        self.max_batch_size = max_batch_size
        self.decoupled = decoupled
        self.stateful = stateful
        self.dynamic_batching = dynamic_batching
        self.max_queue_delay_us = max_queue_delay_us
        self.warmup = warmup
        # Whether device-resident (TPU-shm) requests fuse into device-side
        # batches; off by default — see dynamic_batcher.batchable_request.
        self.batch_device_inputs = batch_device_inputs
        # Whether fn is jax-pure so device groups can fuse concat+forward+
        # split into one jitted dispatch (dynamic_batcher._fused_group_fn).
        self.fused_batching = fused_batching
        self.max_fused_arity = max_fused_arity  # cap on fused group parts
        # Dynamic-batcher admission: queued requests beyond this depth are
        # shed with a retryable 503 (None = unbounded queue).
        self.max_queue_depth = max_queue_depth
        # Config-driven ensemble (reference ensemble_scheduling): ordered
        # steps [{"model_name", "input_map" {composing<-ensemble tensor},
        # "output_map" {composing->ensemble tensor}}].  fn is ignored; the
        # engine chains the composing models (execute -> per-model stats).
        self.ensemble_steps = list(ensemble_steps or [])
        # FLOPs of one forward item (batch row) — lets harnesses report
        # achieved TFLOP/s and MFU (reference perf_analyzer reports only
        # protocol rates; compute accounting is a TPU-charter addition).
        self.flops_per_item = flops_per_item
        # Per-model cache hints (the reference's `response_cache` config
        # block): {"cacheable"/"enable": bool, "ttl_s": float, and for LM
        # models a "prefix_cache" sub-block with the KV prefix-cache
        # knobs}.  None = default behavior (cacheable whenever the server
        # runs a ResponseCache, no per-model TTL).
        self.response_cache = dict(response_cache or {}) or None
        self.config_override = None  # set by repository load with config param
        self.file_overrides = {}
        # optional resource-release hook, called by InferenceEngine.close()
        self.closer = None
        # optional late-bind hook, called by InferenceEngine.add_model with
        # the engine: model-owned subsystems (e.g. the continuous-batching
        # LM engine) pick up the server's metrics registry, tracer, and
        # per-tenant QoS here instead of constructing their own
        self.binder = None
        # validated ensemble DAG (serve/pipeline.py), built at add/load time
        self._dag = None

    def metadata(self):
        return {
            "name": self.name,
            "versions": self.versions,
            "platform": self.platform,
            "inputs": [t.metadata() for t in self.inputs],
            "outputs": [t.metadata() for t in self.outputs],
        }

    def config(self):
        if self.config_override is not None:
            merged = dict(self._base_config())
            merged.update(self.config_override)
            merged["name"] = self.name
            return merged
        return self._base_config()

    def _base_config(self):
        cfg = {
            "name": self.name,
            "platform": self.platform,
            "backend": self.backend,
            "max_batch_size": self.max_batch_size,
            "input": [
                {"name": t.name, "data_type": f"TYPE_{_cfg_type(t.datatype)}", "dims": t.dims}
                for t in self.inputs
            ],
            "output": [
                {"name": t.name, "data_type": f"TYPE_{_cfg_type(t.datatype)}", "dims": t.dims}
                for t in self.outputs
            ],
        }
        if self.dynamic_batching:
            cfg["dynamic_batching"] = {
                "max_queue_delay_microseconds": self.max_queue_delay_us
            }
        if self.decoupled:
            cfg["model_transaction_policy"] = {"decoupled": True}
        if self.stateful:
            cfg["sequence_batching"] = {"max_sequence_idle_microseconds": 60000000}
        if self.flops_per_item:
            # Triton-style config parameters map (string_value entries)
            cfg["parameters"] = {
                "flops_per_item": {"string_value": str(int(self.flops_per_item))}
            }
        if self.ensemble_steps:
            cfg["ensemble_scheduling"] = {
                "step": [
                    {
                        "model_name": s["model_name"],
                        "model_version": s.get("model_version", -1),
                        "input_map": dict(s.get("input_map", {})),
                        "output_map": dict(s.get("output_map", {})),
                    }
                    for s in self.ensemble_steps
                ]
            }
        if self.response_cache is not None:
            cacheable, ttl_s = self.cache_hints()
            block = {"enable": cacheable}
            if ttl_s is not None:
                block["ttl_s"] = ttl_s
            if self.response_cache.get("prefix_cache") is not None:
                block["prefix_cache"] = dict(
                    self.response_cache["prefix_cache"]
                )
            cfg["response_cache"] = block
        return cfg

    def cache_hints(self):
        """(cacheable, ttl_s) from the model's ``response_cache`` block:
        the per-model front-door policy the engine consults before the
        all-models response cache (absent block = cacheable, no TTL
        override).  ``cacheable`` and ``enable`` are accepted synonyms —
        the reference config block spells it ``enable``."""
        rc = self.response_cache or {}
        cacheable = rc.get("cacheable", rc.get("enable", True))
        return bool(cacheable), rc.get("ttl_s")


def _cfg_type(datatype):
    return "STRING" if datatype == "BYTES" else datatype


class ModelStats:
    """Per-model cumulative statistics in the spec's statistics-extension shape."""

    def __init__(self):
        self.lock = threading.Lock()
        self.inference_count = 0
        self.execution_count = 0
        self.last_inference_ms = 0
        self.success_count = 0
        self.success_ns = 0
        self.fail_count = 0
        self.fail_ns = 0
        self.compute_infer_ns = 0
        self.compute_input_ns = 0
        self.compute_output_ns = 0
        self.queue_ns = 0
        # response-cache accounting (the reference surfaces cache_hit /
        # cache_miss durations through the statistics extension)
        self.cache_hit_count = 0
        self.cache_hit_ns = 0
        self.cache_miss_count = 0
        self.cache_miss_ns = 0
        # distributions behind the /metrics histograms: per-request
        # end-to-end duration (success AND failure), per-request batcher
        # queue time, and execution batch size
        self.request_us = Histogram()
        self.queue_us = Histogram()
        self.batch_rows = Histogram(BATCH_BUCKETS)

    def record(self, ok, total_ns, infer_ns, input_ns, output_ns, batch=1):
        with self.lock:
            self.request_us.observe(total_ns / 1000)
            if ok:
                self.inference_count += batch
                self.execution_count += 1
                self.success_count += 1
                self.success_ns += total_ns
                self.compute_infer_ns += infer_ns
                self.compute_input_ns += input_ns
                self.compute_output_ns += output_ns
                self.batch_rows.observe(batch)
                self.last_inference_ms = int(time.time() * 1000)
            else:
                self.fail_count += 1
                self.fail_ns += total_ns

    def record_batched(self, rows, infer_ns, input_ns, output_ns, queue_ns,
                       queue_ns_each=None):
        """One dynamic-batched execution.  Per-request success outcomes are
        recorded separately by record_request_success once rendering finishes;
        failures go through record(False, ...) in execute()."""
        with self.lock:
            self.inference_count += rows
            self.execution_count += 1
            self.compute_infer_ns += infer_ns
            self.compute_input_ns += input_ns
            self.compute_output_ns += output_ns
            self.queue_ns += queue_ns
            self.batch_rows.observe(rows)
            for q_ns in queue_ns_each or ():
                self.queue_us.observe(q_ns / 1000)
            self.last_inference_ms = int(time.time() * 1000)

    def record_request_success(self, total_ns):
        """One successful request served through the batched path.  Failures
        on that path are counted by ``record(False, ...)`` in execute()'s
        except clauses, exactly once, like every other failure."""
        with self.lock:
            self.success_count += 1
            self.success_ns += total_ns
            self.request_us.observe(total_ns / 1000)

    def record_cache_hit(self, total_ns):
        """One request answered from the response cache: a request success
        with zero inferences executed (inference_count untouched)."""
        with self.lock:
            self.success_count += 1
            self.success_ns += total_ns
            self.request_us.observe(total_ns / 1000)
            self.cache_hit_count += 1
            self.cache_hit_ns += total_ns

    def record_cache_miss(self, lookup_ns):
        """One cacheable request that had to execute (the lookup cost is
        what the reference's cache_miss duration measures)."""
        with self.lock:
            self.cache_miss_count += 1
            self.cache_miss_ns += lookup_ns

    def histograms(self):
        """Snapshots of (request_us, queue_us, batch_rows) for /metrics."""
        with self.lock:
            return (
                self.request_us.snapshot(),
                self.queue_us.snapshot(),
                self.batch_rows.snapshot(),
            )

    def to_json(self, name, version):
        with self.lock:
            return {
                "name": name,
                "version": version,
                "last_inference": self.last_inference_ms,
                "inference_count": self.inference_count,
                "execution_count": self.execution_count,
                "inference_stats": {
                    "success": {"count": self.success_count, "ns": self.success_ns},
                    "fail": {"count": self.fail_count, "ns": self.fail_ns},
                    "queue": {"count": self.success_count, "ns": self.queue_ns},
                    "compute_input": {
                        "count": self.success_count,
                        "ns": self.compute_input_ns,
                    },
                    "compute_infer": {
                        "count": self.success_count,
                        "ns": self.compute_infer_ns,
                    },
                    "compute_output": {
                        "count": self.success_count,
                        "ns": self.compute_output_ns,
                    },
                    "cache_hit": {
                        "count": self.cache_hit_count,
                        "ns": self.cache_hit_ns,
                    },
                    "cache_miss": {
                        "count": self.cache_miss_count,
                        "ns": self.cache_miss_ns,
                    },
                },
            }


class SharedMemoryRegistry:
    """Server-side registry of system and TPU shared-memory regions.

    System regions attach by POSIX shm key (``/dev/shm``).  TPU regions carry
    a raw handle (JSON: uuid/pid/device_id/byte_size/staging_key emitted by
    libctpushm.so); same-process handles resolve to the live TpuRegion
    (zero-copy jax.Array access), foreign handles attach the region's native
    host window by shm key (see client_tpu/utils/tpu_shared_memory).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._system = {}
        self._tpu = {}

    # system ---------------------------------------------------------------

    def register_system(self, name, key, offset, byte_size):
        with self._lock:
            if name in self._system:
                old = self._system[name]
                if (old["key"], old["offset"], old["byte_size"]) != (
                    key,
                    offset,
                    byte_size,
                ):
                    raise InferenceServerException(
                        f"shared memory region '{name}' already registered "
                        "with different attributes",
                        status="400",
                    )
                return
            mm = _attach_posix_shm(key, offset + byte_size)
            self._system[name] = {
                "key": key,
                "offset": offset,
                "byte_size": byte_size,
                "mmap": mm,
            }

    def unregister_system(self, name=None):
        with self._lock:
            names = [name] if name else list(self._system)
            for n in names:
                region = self._system.pop(n, None)
                if region is not None:
                    region["mmap"].close()

    def system_status(self, name=None):
        with self._lock:
            regions = {}
            for n, r in self._system.items():
                if name and n != name:
                    continue
                regions[n] = {
                    "name": n,
                    "key": r["key"],
                    "offset": r["offset"],
                    "byte_size": r["byte_size"],
                }
            if name and not regions:
                raise InferenceServerException(
                    f"shared memory region '{name}' is not registered", status="400"
                )
            return regions

    # tpu ------------------------------------------------------------------

    def register_tpu(self, name, raw_handle, device_id, byte_size):
        from client_tpu.utils import tpu_shared_memory as _tpushm

        descriptor = json.loads(
            raw_handle.decode("utf-8") if isinstance(raw_handle, bytes) else raw_handle
        )
        with self._lock:
            if name in self._tpu:
                old = self._tpu[name]
                if (
                    old["descriptor"].get("uuid") == descriptor.get("uuid")
                    and old["byte_size"] == byte_size
                    and old["device_id"] == device_id
                ):
                    return
                raise InferenceServerException(
                    f"TPU shared memory region '{name}' already registered "
                    "with different attributes",
                    status="400",
                )
            # Same-process client (in-process server / C-API analog): resolve
            # the live HBM region through the broker — zero-copy jax.Array
            # access.  Otherwise attach the region's native host window
            # (libctpushm.so) by the shm key in the descriptor.
            region_obj = _tpushm.resolve_inprocess(descriptor)
            if region_obj is None:
                if descriptor.get("staging_key") is None:
                    raise InferenceServerException(
                        f"TPU region '{name}' descriptor carries no host "
                        "window (staging_key); cross-process registration "
                        "requires the native window (PJRT has no "
                        "cross-process buffer export)",
                        status="400",
                    )
                try:
                    region_obj = _tpushm.TpuWindowRegion(descriptor)
                except InferenceServerException as e:
                    raise InferenceServerException(
                        f"unable to attach TPU region '{name}': {e.message()}",
                        status="400",
                    ) from e
            self._tpu[name] = {
                "device_id": device_id,
                "byte_size": byte_size,
                "descriptor": descriptor,
                "region_obj": region_obj,
            }

    def unregister_tpu(self, name=None):
        with self._lock:
            names = [name] if name else list(self._tpu)
            removed = [self._tpu.pop(n, None) for n in names]
        for region in removed:
            if region is None:
                continue
            obj = region.get("region_obj")
            # window attachments are server-owned and must be unmapped;
            # in-process TpuRegions belong to the client (no close method)
            if obj is not None and hasattr(obj, "close"):
                obj.close()

    def tpu_status(self, name=None):
        with self._lock:
            regions = {}
            for n, r in self._tpu.items():
                if name and n != name:
                    continue
                regions[n] = {
                    "name": n,
                    "device_id": r["device_id"],
                    "byte_size": r["byte_size"],
                }
            if name and not regions:
                raise InferenceServerException(
                    f"TPU shared memory region '{name}' is not registered",
                    status="400",
                )
            return regions

    # data access ----------------------------------------------------------

    def _find(self, region_name):
        """System region (mmap, base offset) or raises.  TPU regions are
        dispatched through their region_obj before this is consulted."""
        region = self._system.get(region_name)
        if region is None:
            raise InferenceServerException(
                f"shared memory region '{region_name}' is not registered",
                status="400",
            )
        return region, region["offset"]

    def read_tensor(self, region_name, offset, byte_size, datatype, shape):
        """Resolve an input tensor from a region.  In-process TPU regions
        return the live jax.Array (zero-copy); window attachments and system
        regions decode from bytes."""
        with self._lock:
            region = self._tpu.get(region_name)
            obj = region.get("region_obj") if region else None
        if obj is not None:
            try:
                return obj.read_array(offset, byte_size, datatype, shape)
            except InferenceServerException as e:
                raise InferenceServerException(e.message(), status="400") from e
        raw = self.read(region_name, offset, byte_size)
        return from_wire_bytes(raw, datatype, shape)

    def write_tensor(self, region_name, offset, arr, datatype, max_byte_size):
        """Write an output tensor into a region; returns bytes written.
        In-process TPU regions store the device array directly (no D2H)."""
        with self._lock:
            region = self._tpu.get(region_name)
            obj = region.get("region_obj") if region else None
        if obj is not None:
            if not (isinstance(arr, np.ndarray) and arr.dtype == np.object_):
                from client_tpu.utils import triton_to_np_dtype

                want = triton_to_np_dtype(datatype)
                if want is not None and arr.dtype != np.dtype(want):
                    arr = arr.astype(want)  # device-side cast, stays resident
                nbytes = arr.dtype.itemsize * int(np.prod(arr.shape))
            else:
                nbytes = len(to_wire_bytes(arr, datatype))
            if nbytes > max_byte_size:
                raise InferenceServerException(
                    f"output needs {nbytes} bytes but region '{region_name}' "
                    f"mapping holds {max_byte_size}",
                    status="400",
                )
            obj.write_array(offset, arr)
            return nbytes
        raw = to_wire_bytes(np.asarray(arr), datatype)
        if len(raw) > max_byte_size:
            raise InferenceServerException(
                f"output needs {len(raw)} bytes but region '{region_name}' "
                f"mapping holds {max_byte_size}",
                status="400",
            )
        self.write(region_name, offset, raw)
        return len(raw)

    def read(self, region_name, offset, byte_size):
        with self._lock:
            tpu = self._tpu.get(region_name)
            obj = tpu.get("region_obj") if tpu else None
        if obj is not None:
            # byte-addressable on both faces (may sync dirty device slots)
            return obj.read(offset, byte_size)
        with self._lock:
            region, base = self._find(region_name)
            if offset + byte_size > region["byte_size"]:
                raise InferenceServerException(
                    f"read of {byte_size} bytes at offset {offset} overruns "
                    f"region '{region_name}'",
                    status="400",
                )
            mm = region["mmap"]
            return bytes(mm[base + offset : base + offset + byte_size])

    def write(self, region_name, offset, data):
        with self._lock:
            tpu = self._tpu.get(region_name)
            obj = tpu.get("region_obj") if tpu else None
        if obj is not None:
            obj.write(offset, data)
            return
        with self._lock:
            region, base = self._find(region_name)
            if offset + len(data) > region["byte_size"]:
                raise InferenceServerException(
                    f"write of {len(data)} bytes at offset {offset} overruns "
                    f"region '{region_name}'",
                    status="400",
                )
            mm = region["mmap"]
            mm[base + offset : base + offset + len(data)] = data

    def close(self):
        self.unregister_system()
        self.unregister_tpu()


def _attach_posix_shm(key, length):
    path = "/dev/shm/" + key.lstrip("/")
    try:
        fd = os.open(path, os.O_RDWR)
    except OSError as e:
        raise InferenceServerException(
            f"unable to open shared memory region key '{key}': {e}", status="400"
        ) from e
    try:
        return mmap.mmap(fd, length)
    except ValueError as e:
        raise InferenceServerException(
            f"unable to map {length} bytes of region key '{key}': {e}", status="400"
        ) from e
    finally:
        os.close(fd)


class BusyTracker:
    """Wall-clock union of model-execution intervals (server duty cycle).

    The TPU analog of the reference's GPU-utilization scrape
    (metrics_manager.h:44-91): overlapping executions are unioned, so
    busy_ns/elapsed is the fraction of wall time the server had at least one
    model execution in flight — "is the chip being fed?" as a counter."""

    def __init__(self):
        self._lock = threading.Lock()
        self._active = 0
        self._since = 0
        self._busy_ns = 0

    def begin(self):
        with self._lock:
            if self._active == 0:
                self._since = time.monotonic_ns()
            self._active += 1

    def end(self):
        with self._lock:
            self._active -= 1
            if self._active == 0:
                self._busy_ns += time.monotonic_ns() - self._since

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def busy_ns(self):
        with self._lock:
            busy = self._busy_ns
            if self._active:
                busy += time.monotonic_ns() - self._since
            return busy


class _InflightStream:
    """Iterator adapter releasing one in-flight slot exactly once, when
    the wrapped decoupled-response generator is exhausted, fails, is
    closed, or is garbage-collected.  A plain wrapper generator would leak
    the slot when never started (its ``finally`` would not run) — e.g. a
    frontend that rejects the request before iterating."""

    def __init__(self, gen, release):
        self._gen = gen
        self._release = release
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._gen)
        except BaseException:  # StopIteration included: stream is over
            self._finish()
            raise

    def close(self):
        try:
            self._gen.close()
        finally:
            self._finish()

    def _finish(self):
        if not self._done:
            self._done = True
            self._release()

    def __del__(self):
        try:
            self._finish()
        except Exception:  # pragma: no cover - interpreter teardown
            pass


class InferenceEngine:
    """Model repository + request execution shared by the HTTP/gRPC frontends.

    Overload admission control (``max_inflight``) and graceful drain
    (:meth:`drain`) both shed work with a *retryable* 503/``UNAVAILABLE``
    so client-side retry policies (client_tpu.resilience) and server-side
    shedding compose: a shed request backs off and lands once capacity
    returns or on another replica.
    """

    def __init__(
        self,
        models=None,
        strict_model_config=True,
        max_sequence_idle_s=60.0,
        max_inflight=None,
        response_cache=None,
        coalescing=False,
        qos=None,
        fleet=None,
        slo=None,
        flight=None,
    ):
        self._lock = threading.Lock()
        self._models = {}
        self._ready = {}
        self._stats = {}
        self._batchers = {}
        self._pipeline = None  # lazy ensemble DAG scheduler
        # Admission control: cap on concurrently executing requests (None =
        # unbounded).  Work beyond the cap is rejected retryably (503).
        self.max_inflight = max_inflight
        self._inflight = 0
        self._draining = False
        self._flight_cv = threading.Condition()
        self.busy = BusyTracker()
        self._busy_observer = CompletionObserver(name="busy-observer")
        self.shm = SharedMemoryRegistry()
        self._sequences = {}
        self.max_sequence_idle_s = max_sequence_idle_s
        self.trace_settings = {
            k: (list(v) if isinstance(v, list) else v)
            for k, v in TRACE_SETTING_DEFAULTS.items()
        }
        # request tracing (trace extension) + resilience counters: the
        # tracer reads trace_settings live; the registry collects shed and
        # drain counters for /metrics
        self.tracer = Tracer(self.trace_settings)
        self.metrics = Registry()
        # Flight recorder (serve/flight.py): a bounded ring of recent
        # spans + anomaly events dumped on demand (/v2/debug/flight) and
        # automatically on SLO breach / engine wedge / chaos invariant
        # failure — postmortems never depend on tracing having been on.
        self.flight = flight if flight is not None else FlightRecorder(
            registry=self.metrics
        )
        self.tracer.on_complete = self.flight.note_span
        # Continuous profiler (serve/prof.py): always-on per-tick phase
        # timings + MFU attribution.  The unary execute path commits its
        # pre-measured splits here; LM schedulers keep their own
        # profiler and are adopted through Model.binder so
        # /v2/debug/prof and flight dumps cover every engine.
        self.prof = PhaseProfiler(name="serve", registry=self.metrics)
        # the frontends' wire-path ticks (deserialize/wait/serialize/
        # send) keep their own ring: their "wait" phase CONTAINS the
        # engine's execute ticks, so sharing a ring would double-count
        self.wire_prof = PhaseProfiler(name="wire", registry=self.metrics)
        self.prof.adopt(self.wire_prof)
        if self.flight.prof is None:
            self.flight.prof = self.prof
        # SLO watchdog (serve/slo.py): streaming latency quantile
        # sketches per (model, tenant), ctpu_slo_* gauges, breach counter
        # + flight dump.  slo=None builds the observation-only default;
        # pass a configured SloWatchdog to arm objectives, or False to
        # disable entirely.
        if slo is None:
            from client_tpu.serve.slo import SloWatchdog

            slo = SloWatchdog()
        self.slo = slo or None
        if self.slo is not None:
            if self.slo.registry is None:
                self.slo.registry = self.metrics
            if self.slo.flight is None:
                self.slo.flight = self.flight
        # Multi-tenant front door (serve/frontdoor.py): response cache,
        # in-flight coalescing, per-tenant QoS.  All opt-in; their metrics
        # land in this engine's registry unless already bound elsewhere.
        self.response_cache = response_cache
        if response_cache is not None and response_cache.registry is None:
            response_cache.registry = self.metrics
        self.qos = qos
        if qos is not None and qos.registry is None:
            qos.registry = self.metrics
        self._coalescer = None
        if coalescing:
            from client_tpu.serve.frontdoor import Coalescer

            self._coalescer = Coalescer(registry=self.metrics)
        # Cross-replica cache tier (serve/fleet.py): a local response-
        # cache miss consults peer replicas before dispatching, the LM
        # engine's prefix cache spans the fleet (wired per-model through
        # Model.binder), and tenant quotas account fleet-wide via gossip.
        self.fleet = None
        if fleet is not None:
            fleet.attach(self)
        self.log_settings = {
            "log_file": "",
            "log_info": True,
            "log_warning": True,
            "log_error": True,
            "log_verbose_level": 0,
            "log_format": "default",
        }
        for model in models or []:
            self.add_model(model)

    # repository -----------------------------------------------------------

    def add_model(self, model, ready=True):
        from client_tpu.serve.pipeline import build_dag

        # Validation and installation are ONE critical section: a DAG
        # validated against a repository snapshot that can mutate before
        # the install would let a concurrent add/load leave a READY
        # ensemble whose DAG describes a since-replaced composing model.
        # build_dag is pure spec walking — nothing blocks under the lock.
        with self._lock:
            known = dict(self._models)
            known[model.name] = model
            if model.ensemble_steps:
                # Ensembles validate at ADD time (cycles, unknown composing
                # models, unmapped/dangling tensors, dtype/shape
                # mismatches, sequence/decoupled composing models) -> 400
                # here, never a surprise at infer time.  Composing models
                # must already be in the repository.
                model._dag = build_dag(model, known.get)
            # A swap must not leave a loaded ensemble silently broken:
            # every ready ensemble composing over this name revalidates
            # against the replacement.  A compatible swap refreshes the
            # dependent's DAG; an incompatible one marks the dependent NOT
            # READY (infer gets the engine's clean 400, and reloading it
            # surfaces the real mismatch via load_model's revalidation) —
            # never wrong-typed bytes on the wire.  Direct dependents
            # only: an ensemble's own declared specs don't change unless
            # it is itself re-added.
            for n, dep in self._models.items():
                if (
                    n == model.name or not dep.ensemble_steps
                    or not self._ready.get(n)
                    or all(
                        s.get("model_name") != model.name
                        for s in dep.ensemble_steps
                    )
                ):
                    continue
                try:
                    dep._dag = build_dag(dep, known.get)
                except InferenceServerException:
                    self._ready[n] = False
            self._models[model.name] = model
            self._ready[model.name] = ready
            self._stats.setdefault(model.name, ModelStats())
            # A replaced model must not keep serving through the old batcher.
            stale = self._batchers.pop(model.name, None)
        if stale is not None:
            stale.close()
        self._invalidate_cache()
        # outside the repository lock: binders may take their own locks
        # (registry/QoS) and must never nest under self._lock
        if model.binder is not None:
            model.binder(self)
        if model.dynamic_batching and model.warmup:
            self._batcher_for(model).warmup(model.inputs)

    def _model_lookup(self, extra=None):
        """Name -> Model resolver over the current repository snapshot (the
        model being added rides along so self-reference is detectable)."""
        with self._lock:
            known = dict(self._models)
        if extra is not None:
            known[extra.name] = extra
        return known.get

    def _invalidate_cache(self):
        """Repository mutations (add/load/unload) drop the whole response
        cache: the digest keys on request CONTENT, so a model swapped with
        new weights or a config/file override would keep answering from its
        pre-mutation cache forever (repository changes are rare; a full
        clear is cheap and always correct)."""
        if self.response_cache is not None:
            self.response_cache.clear()

    def get_model(self, name, version=""):
        with self._lock:
            model = self._models.get(name)
            if model is None or not self._ready.get(name):
                raise InferenceServerException(
                    f"Request for unknown model: '{name}' is not found", status="400"
                )
            if version and version not in model.versions:
                raise InferenceServerException(
                    f"Request for unknown model version: '{name}' version "
                    f"{version} is not found",
                    status="400",
                )
            return model

    def model_ready(self, name, version=""):
        with self._lock:
            model = self._models.get(name)
            return bool(
                model
                and self._ready.get(name)
                and (not version or version in model.versions)
            )

    def load_model(self, name, config_override=None, files=None):
        from client_tpu.serve.pipeline import build_dag

        with self._lock:
            if name not in self._models:
                raise InferenceServerException(
                    f"failed to load '{name}', no such model", status="400"
                )
            if files and config_override is None:
                raise InferenceServerException(
                    "load with file override requires a config override too",
                    status="400",
                )
            model = self._models[name]
            if model.ensemble_steps:
                # revalidate against the CURRENT repository (composing
                # models may have been swapped since add): a broken
                # ensemble fails the load with a 400 and is not marked
                # ready.  Atomic with the ready flip — see add_model.
                model._dag = build_dag(model, dict(self._models).get)
            model.config_override = config_override
            model.file_overrides = files or {}
            self._ready[name] = True
        self._invalidate_cache()

    def unload_model(self, name):
        with self._lock:
            if name not in self._models:
                raise InferenceServerException(
                    f"failed to unload '{name}', no such model", status="400"
                )
            self._ready[name] = False
            batcher = self._batchers.pop(name, None)
        if batcher is not None:
            batcher.close()
        self._invalidate_cache()

    def repository_index(self, ready_only=False):
        with self._lock:
            index = []
            for name, model in sorted(self._models.items()):
                is_ready = self._ready.get(name, False)
                if ready_only and not is_ready:
                    continue
                index.append(
                    {
                        "name": name,
                        "version": model.versions[-1],
                        "state": "READY" if is_ready else "UNAVAILABLE",
                        "reason": "",
                    }
                )
            return index

    def statistics(self, name="", version=""):
        with self._lock:
            stats = []
            for n, model in sorted(self._models.items()):
                if name and n != name:
                    continue
                stats.append(
                    self._stats[n].to_json(n, version or model.versions[-1])
                )
            if name and not stats:
                raise InferenceServerException(
                    f"Request for unknown model: '{name}' is not found", status="400"
                )
            return stats

    def stats_objects(self):
        """(name, version, ModelStats) per model, for /metrics histograms."""
        with self._lock:
            return [
                (n, model.versions[-1], self._stats[n])
                for n, model in sorted(self._models.items())
            ]

    # observability: trace settings / live gauges ----------------------------

    def update_trace_settings(self, updates):
        """Apply a trace-settings update through the canonical schema (the
        single normalization point both frontends share — see
        serve/tracing.normalize_trace_settings) and return the settings."""
        normalized = normalize_trace_settings(updates)
        with self._lock:
            self.trace_settings.update(normalized)
        if "trace_count" in normalized:
            # the reference trace API restarts the budget on update
            self.tracer.reset_budget()
        return self.trace_settings

    def queue_depths(self):
        """Dynamic-batcher queue depth per model (live gauge)."""
        with self._lock:
            batchers = dict(self._batchers)
        return {name: b.queue_depth() for name, b in batchers.items()}

    def tenant_queue_depths(self):
        """{(model, tenant): queued count} across batcher fair-queue lanes
        (the per-tenant /metrics queue gauge)."""
        with self._lock:
            batchers = dict(self._batchers)
        out = {}
        for name, batcher in batchers.items():
            for tenant, depth in batcher.queue_depths_by_tenant().items():
                out[(name, tenant)] = depth
        return out

    def inflight_count(self):
        with self._flight_cv:
            return self._inflight

    # lifecycle: readiness / drain ------------------------------------------

    def ready(self):
        """Server-level readiness: False once drain() has begun (the load
        balancer's signal to stop routing here)."""
        with self._flight_cv:
            return not self._draining

    def drain(self, timeout_s=None):
        """Graceful drain: stop admitting new work (readiness flips false,
        new requests are rejected with retryable 503), then wait for every
        in-flight request to finish.  Returns True when fully drained
        within *timeout_s* (None = wait indefinitely)."""
        deadline = (
            time.monotonic() + timeout_s if timeout_s is not None else None
        )
        self.metrics.inc(
            "ctpu_drain_total",
            help_="Graceful drains initiated",
        )
        drained = True
        with self._flight_cv:
            self._draining = True
            while self._inflight:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        drained = False
                        break
                self._flight_cv.wait(timeout=remaining)
        # Planned retire: replicate every live sequence into the fleet
        # tier (timed-out drains included — stranded sequence state is
        # exactly what the tier exists to carry).  Peer pushes run with
        # no engine lock held.
        fleet = self.fleet
        if fleet is not None:
            for snapshot in self.export_sequences():
                try:
                    fleet.publish_sequence(snapshot)
                except Exception:  # pragma: no cover - defensive
                    pass
        return drained

    def _admit(self):
        """One request enters execution, or is shed with a retryable 503."""
        shed_reason = None
        with self._flight_cv:
            if self._draining:
                shed_reason = "draining"
            elif (
                self.max_inflight is not None
                and self._inflight >= self.max_inflight
            ):
                shed_reason = "overload"
            else:
                self._inflight += 1
        if shed_reason is None:
            return
        self.metrics.inc(
            "ctpu_requests_shed_total", {"reason": shed_reason},
            help_="Requests shed with a retryable 503",
        )
        if shed_reason == "draining":
            raise InferenceServerException(
                "server is draining and not accepting new requests",
                status="503",
            )
        raise InferenceServerException(
            f"server overloaded: {self._inflight} requests in flight "
            f"(limit {self.max_inflight}); retry after backoff",
            status="503",
        )

    def _release(self):
        with self._flight_cv:
            self._inflight -= 1
            self._flight_cv.notify_all()

    # execution ------------------------------------------------------------

    def execute(self, model_name, model_version, request, binary_section,
                trace=None, tenant=""):
        """Run one inference request through the front door + admission.

        *request* is the JSON-form header dict; *binary_section* the raw bytes
        after the header. Returns (response_dict, binary_blobs) — for decoupled
        models, a list of such tuples.  *trace* is an optional RequestTrace
        the frontend sampled; the engine (and the dynamic batcher) record the
        queue/compute timeline onto it.  *tenant* is the caller identity from
        the ``x-tenant-id`` header/metadata key (empty = default tenant).

        Order of the front door: response-cache lookup → in-flight
        coalescing → per-tenant QoS admission (429 with Retry-After) →
        global admission (503) → execution.  Cache hits and coalesced
        followers never consume an execution slot OR a tenant quota slot —
        that is the point: serving a hot key from the cache costs the
        server almost nothing, so shedding it would be self-defeating
        (they still count in the per-tenant request series).

        The whole request runs with *trace* installed as the thread's
        active trace (serve/tracing.push_trace), so fleet peer RPCs made
        while serving it — prefix/cache/sequence lookups, the durable
        snapshot push — record child spans under its trace id.  The SLO
        watchdog observes every completion; 4xx rejections count as
        latency only (the client's fault, not a server error).
        """
        # the CM form costs ~1us/request: on the untraced hot path the
        # thread-local needs no touch at all (the 2% tracing-overhead
        # budget is measured against the sub-ms headline request)
        if trace is None:
            return self._execute_measured(
                model_name, model_version, request, binary_section,
                trace, tenant,
            )
        with push_trace(trace):
            return self._execute_measured(
                model_name, model_version, request, binary_section,
                trace, tenant,
            )

    def _execute_measured(self, model_name, model_version, request,
                          binary_section, trace, tenant):
        """SLO accounting bracket: every completion (or failure) of one
        request lands in the watchdog's sketch; 5xx/transport count
        against the error-rate objective, 4xx as latency only."""
        t0 = time.monotonic_ns()
        status = ""
        try:
            return self._execute_request(
                model_name, model_version, request, binary_section,
                trace, tenant, t0,
            )
        except InferenceServerException as e:
            status = str(e.status())
            raise
        except BaseException:
            status = "500"
            raise
        finally:
            slo = self.slo
            if slo is not None:
                slo.observe(
                    model_name, tenant,
                    (time.monotonic_ns() - t0) / 1e9,
                    error=bool(status) and not status.startswith("4"),
                )

    def _execute_request(self, model_name, model_version, request,
                         binary_section, trace, tenant, t0):
        if trace is not None:
            trace.tenant = tenant
            trace.event("QUEUE_START")
        front = self._front_key(model_name, model_version, request,
                                binary_section)
        if front is not None:
            key, cacheable, ttl_s = front
            return self._front_door(
                key, model_name, model_version, request, binary_section,
                trace, tenant, t0, cacheable, ttl_s,
            )
        qos_release = self.qos.admit(tenant) if self.qos is not None else None
        try:
            result = self._execute_slot(
                model_name, model_version, request, binary_section,
                trace, tenant, extra_release=qos_release,
            )
            if isinstance(result, _InflightStream):
                qos_release = None  # the stream owns the QoS slot now
            return result
        finally:
            if qos_release is not None:
                qos_release()

    def _front_key(self, model_name, model_version, request, binary_section):
        """``(digest, cacheable, ttl_s)`` for this request, or None when
        the front door does not apply (no cache or coalescer configured;
        decoupled or stateful model; sequence/shared-memory request;
        unknown model — the normal path raises the proper error).

        ``cacheable``/``ttl_s`` come from the model's per-model
        ``response_cache`` config block: a model that opts out of caching
        still coalesces (a hot key is a hot key), and a model with a
        freshness bound caches with its own TTL instead of the cache-wide
        default."""
        if self.response_cache is None and self._coalescer is None:
            return None
        with self._lock:
            model = self._models.get(model_name)
            if model is None or not self._ready.get(model_name):
                return None
        if model.decoupled or model.stateful:
            return None
        cacheable, ttl_s = model.cache_hints()
        if not cacheable and self._coalescer is None:
            return None  # nothing left for the front door to do
        from client_tpu.serve.frontdoor import request_digest

        key = request_digest(model_name, model_version, request,
                             binary_section)
        if key is None:
            return None
        return key, cacheable, ttl_s

    def _front_door(self, key, model_name, model_version, request,
                    binary_section, trace, tenant, t0, cacheable=True,
                    ttl_s=None):
        """Serve one cacheable unary request: cache hit, coalesced follower,
        or (leader / uncoalesced) QoS-admitted execution + cache fill."""
        stats = self._stats[model_name]
        use_cache = self.response_cache is not None and cacheable
        lookup_ns = 0
        if use_cache:
            lookup0 = time.monotonic_ns()
            cached = self.response_cache.get(key)
            lookup_ns = time.monotonic_ns() - lookup0
            if cached is not None:
                if trace is not None:
                    trace.event("CACHE_HIT")
                if self.qos is not None:
                    self.qos.note(tenant)
                fleet = self.fleet
                if fleet is not None:
                    # hot-entry signal for proactive replication: a pure
                    # host-side counter bump, never a peer RPC
                    fleet.note_cache_hit(key)
                response, blobs = cached
                stats.record_cache_hit(time.monotonic_ns() - t0)
                return _stamp_id(response, request), blobs
        if self._coalescer is None:
            if use_cache:
                fleet_hit = self._fleet_cached(key, ttl_s)
                if fleet_hit is not None:
                    return self._serve_fleet_hit(
                        fleet_hit, request, trace, tenant, stats, t0
                    )
            result = self._front_dispatch(
                model_name, model_version, request, binary_section, trace,
                tenant,
            )
            if not isinstance(result, tuple):
                # the model was hot-swapped to a decoupled/stateful shape
                # between the front-key check and execution: a stream is
                # not cacheable — hand it straight to the caller
                return result
            # a miss is a request that EXECUTED after missing: coalesced
            # followers and shed requests never dispatched, so counting
            # them would report a near-0% hit rate during the exact storms
            # the cache absorbs
            if use_cache:
                stats.record_cache_miss(lookup_ns)
                self._cache_fill(key, (_strip_id(result[0]), result[1]),
                                 ttl_s)
            return result
        while True:
            is_leader, flight = self._coalescer.join(key)
            if not is_leader:
                # identical request already dispatching: wait for its
                # result (the leader ALWAYS completes the flight — so
                # this wait is bounded by the leader's execution)
                flight.event.wait()
                if flight.retry:
                    # the leader was shed by ITS OWN tenant's admission:
                    # that 429 is tenant identity, not request content —
                    # re-contend so a compliant tenant's request becomes
                    # the next leader under its own quota
                    continue
                if trace is not None:
                    trace.event("COALESCED")
                if self.qos is not None:
                    self.qos.note(tenant)
                if flight.error is not None:
                    stats.record(False, time.monotonic_ns() - t0, 0, 0, 0)
                    raise flight.error
                response, blobs = flight.result
                stats.record_request_success(time.monotonic_ns() - t0)
                return _stamp_id(response, request), blobs
            if use_cache:
                # LEADER-only fleet lookup (followers coalesce onto it):
                # a hot key's peer fan-out stays one lookup per flight,
                # not one per request in the herd
                fleet_hit = self._fleet_cached(key, ttl_s)
                if fleet_hit is not None:
                    self._coalescer.publish(key, flight, fleet_hit)
                    return self._serve_fleet_hit(
                        fleet_hit, request, trace, tenant, stats, t0
                    )
            try:
                result = self._front_dispatch(
                    model_name, model_version, request, binary_section,
                    trace, tenant,
                )
            except InferenceServerException as e:
                from client_tpu.resilience import is_connection_level

                if e.status() == "429" or is_connection_level(e):
                    # tenant-scoped QoS rejection — or a leader that died
                    # WITH its transport (replica/peer death mid-dispatch):
                    # neither says anything about the request CONTENT, so
                    # followers re-contend (the next leader lands on a
                    # surviving path) instead of inheriting the error
                    self._coalescer.retry_followers(key, flight)
                    raise
                # content-scoped errors fan out to every follower: a
                # byte-identical request would have failed identically,
                # and N retries of it is the herd coalescing prevents
                self._coalescer.fail(key, flight, e)
                raise
            except BaseException as e:
                self._coalescer.fail(key, flight, e)
                raise
            if not isinstance(result, tuple):
                # hot-swap TOCTOU (see the uncoalesced branch): nothing
                # shareable was produced — followers re-contend and
                # re-evaluate cacheability against the swapped model
                self._coalescer.retry_followers(key, flight)
                return result
            # publish/cache the id-less rendering: followers and later
            # hits stamp their own request id — under a guard, because a
            # flight left incomplete here would strand every follower on
            # an untimed wait
            try:
                if use_cache:
                    stats.record_cache_miss(lookup_ns)  # leader executed
                shared = (_strip_id(result[0]), result[1])
            except BaseException as e:  # pragma: no cover - defensive
                self._coalescer.fail(key, flight, e)
                raise
            self._coalescer.publish(key, flight, shared)
            if use_cache:
                self._cache_fill(key, shared, ttl_s)
            return result

    def _front_dispatch(self, model_name, model_version, request,
                        binary_section, trace, tenant):
        """One front-door request that missed every fast path: per-tenant
        QoS admission (429) then a real execution slot.  Always unary —
        the front door never applies to decoupled models."""
        qos_release = self.qos.admit(tenant) if self.qos is not None else None
        try:
            return self._execute_slot(
                model_name, model_version, request, binary_section, trace,
                tenant,
            )
        finally:
            if qos_release is not None:
                qos_release()

    def _fleet_cached(self, key, ttl_s):
        """Peer-replica response-cache lookup for a local miss: the
        id-less ``(response, blobs)`` rendering, filled into the local
        cache, or None.  The peer RPC runs on the request thread with NO
        engine lock held and is bounded by the tier's fan-out x timeout
        (breaker-gated: a dead fleet degrades to local-only)."""
        fleet = self.fleet
        if fleet is None or self.response_cache is None:
            return None
        remote = fleet.cache_lookup(key)
        if remote is None:
            return None
        response, blobs = remote
        self.response_cache.put(key, response, blobs, ttl_s=ttl_s)
        self.metrics.inc(
            "ctpu_fleet_cache_hits_total",
            help_=FLEET_HELP["ctpu_fleet_cache_hits_total"],
        )
        return response, blobs

    def _serve_fleet_hit(self, shared, request, trace, tenant, stats, t0):
        """Render one fleet cache hit exactly like a local hit: own
        request id stamped, tenant request counted, no execution slot."""
        if trace is not None:
            trace.event("CACHE_HIT")
        if self.qos is not None:
            self.qos.note(tenant)
        response, blobs = shared
        stats.record_cache_hit(time.monotonic_ns() - t0)
        return _stamp_id(response, request), blobs

    def _cache_fill(self, key, shared, ttl_s=None):
        """Store one id-less ``(response, blobs)`` rendering, under the
        model's own TTL when its config block sets one."""
        if self.response_cache is not None:
            self.response_cache.put(key, shared[0], shared[1], ttl_s=ttl_s)

    def _execute_slot(self, model_name, model_version, request,
                      binary_section, trace, tenant, extra_release=None):
        """The pre-front-door execution path: global admission + execution.
        ``extra_release`` (the QoS slot) transfers to the returned stream
        for decoupled results."""
        self._admit()
        streamed = False
        try:
            result = self._execute_admitted(
                model_name, model_version, request, binary_section, trace,
                tenant,
            )
            if not isinstance(result, (tuple, list)):  # decoupled generator
                streamed = True

                # the stream stays counted as in-flight (engine slot AND
                # tenant slot) until the consumer exhausts, closes, or
                # drops it — drain must not cut a stream mid-generation
                def release(engine=self, extra=extra_release):
                    engine._release()
                    if extra is not None:
                        extra()

                return _InflightStream(result, release)
            return result
        finally:
            if not streamed:
                self._release()

    def _execute_admitted(self, model_name, model_version, request,
                          binary_section, trace=None, tenant=""):
        model = self.get_model(model_name, model_version)
        stats = self._stats[model_name]
        t0 = time.monotonic_ns()
        try:
            t_in0 = time.monotonic_ns()
            # trace timestamps use the wall clock (comparable with client
            # spans); queue/compute events are emitted once the scheduling
            # path is known — the batcher owns them on the batched path
            w_in0 = time.time_ns() if trace is not None else 0
            inputs = self._gather_inputs(model, request, binary_section)
            params = request.get("parameters", {}) or {}
            context = self._sequence_context(params)
            if context is not None:
                if model.decoupled and (
                    params.get("sequence_durable")
                    or params.get("sequence_step")
                ):
                    # the commit path (step counter, retained rendering,
                    # snapshot push) only exists on the unary direct
                    # path: pretending otherwise would silently drop the
                    # durability the client asked for
                    raise InferenceServerException(
                        f"{model.name}: sequence_durable/sequence_step "
                        "apply to unary stateful models only — decoupled "
                        "streams do not replicate sequence state",
                        status="400",
                    )
                replayed = self._sequence_replay(context, params, request)
                if replayed is not None:
                    # duplicate declared step: answer from the retained
                    # rendering without re-applying (exactly-once resume)
                    stats.record_request_success(time.monotonic_ns() - t0)
                    return replayed
            t_in1 = time.monotonic_ns()
            w_in1 = time.time_ns() if trace is not None else 0
            if model.ensemble_steps:
                if trace is not None:
                    trace.event("QUEUE_END", w_in0)
                    trace.event("COMPUTE_START", w_in0)
                    trace.event("COMPUTE_INPUT_END", w_in1)
                # DAG scheduler (serve/pipeline.py): concurrent independent
                # steps, per-step spans/stats, device-resident intermediates.
                # Request params (minus ensemble-reserved keys) thread
                # through to every composing model.  work_ns — the summed
                # per-step durations — is recorded as the ensemble's
                # compute_infer so composing stats reconcile with ensemble
                # totals in the statistics extension.
                result, work_ns = self._pipeline_runner().run(
                    model, inputs, params, trace=trace, tenant=tenant
                )
                t_inf1 = time.monotonic_ns()
                if trace is not None:
                    trace.event("COMPUTE_OUTPUT_START")
                rendered = self._render_response(
                    model, model_version, request, result
                )
                t1 = time.monotonic_ns()
                if trace is not None:
                    trace.event("COMPUTE_END")
                stats.record(
                    True, t1 - t0, work_ns, t_in1 - t_in0, t1 - t_inf1,
                    batch=_batch_of(model, request),
                )
                # the profiler reuses the timestamps stats already took:
                # zero added clocks on the hot path
                self.prof.commit(
                    "ensemble", (t1 - t0) / 1e9,
                    phases={
                        "host": (t_in1 - t_in0) / 1e9,
                        "compute": work_ns / 1e9,
                        "render": (t1 - t_inf1) / 1e9,
                    },
                    model=model.name,
                    items=_batch_of(model, request),
                    flops_per_item=model.flops_per_item,
                )
                return rendered
            if _batchable_request(model, inputs, params, context, request):
                # The batcher records execution-level statistics (and the
                # trace's QUEUE_END/COMPUTE_* events at dispatch/completion);
                # per-request success is recorded here, and any failure
                # (batched execution or rendering) falls through to the
                # except clauses below so it is counted exactly once.
                weight = (
                    self.qos.weight(tenant) if self.qos is not None else 1.0
                )
                result = self._batcher_for(model).submit(
                    inputs, trace=trace, tenant=tenant, weight=weight
                )
                rendered = self._render_response(
                    model, model_version, request, result
                )
                stats.record_request_success(time.monotonic_ns() - t0)
                return rendered
            if trace is not None:
                trace.event("QUEUE_END", w_in0)
                trace.event("COMPUTE_START", w_in0)
                trace.event("COMPUTE_INPUT_END", w_in1)
            if model.decoupled:
                # LAZY stream: responses render as the model produces them,
                # so the first token reaches the wire at first-token time —
                # materializing the whole generation first would make
                # time-to-first-token equal total generation time (64 host-
                # driven decode steps over a tunneled chip = seconds).
                return self._decoupled_stream(
                    model, model_version, request, inputs, params, context,
                    stats, t0, t_in0, t_in1, trace, tenant,
                )
            # Direct path: the busy span opens at dispatch and is closed by
            # the observer at device completion (async results) or right
            # after rendering (host results already materialized) — duty
            # cycle measures device occupancy, not dispatch-issue time.
            self.busy.begin()
            watched = False
            try:
                result = model.fn(inputs, params, context)
                t_inf1 = time.monotonic_ns()
                if trace is not None:
                    trace.event("COMPUTE_OUTPUT_START")
                rendered = self._render_response(
                    model, model_version, request, result
                )
                self._busy_observer.watch(result, self.busy.end)
                watched = True
            finally:
                if not watched:
                    self.busy.end()
            t1 = time.monotonic_ns()
            if trace is not None:
                trace.event("COMPUTE_END")
            stats.record(
                True, t1 - t0, t_inf1 - t_in1, t_in1 - t_in0, t1 - t_inf1,
                batch=_batch_of(model, request),
            )
            # pre-measured splits (same timestamps stats used) fold into
            # the continuous profiler without touching another clock
            self.prof.commit(
                "unary", (t1 - t0) / 1e9,
                phases={
                    "host": (t_in1 - t_in0) / 1e9,
                    "compute": (t_inf1 - t_in1) / 1e9,
                    "render": (t1 - t_inf1) / 1e9,
                },
                model=model.name,
                items=_batch_of(model, request),
                flops_per_item=model.flops_per_item,
            )
            if context is not None:
                # applied-step accounting + durable snapshot replication
                # (peer push BEFORE the response leaves this method)
                self._sequence_commit(context, params, rendered)
            return rendered
        except InferenceServerException:
            stats.record(False, time.monotonic_ns() - t0, 0, 0, 0)
            raise
        except Exception as e:
            stats.record(False, time.monotonic_ns() - t0, 0, 0, 0)
            raise InferenceServerException(
                f"{model_name}: execution failed: {e}", status="500", debug_details=e
            ) from e

    def _decoupled_stream(self, model, model_version, request, inputs,
                          params, context, stats, t0, t_in0, t_in1,
                          trace=None, tenant=""):
        """Generator of (response_dict, blobs) for a decoupled model.

        Exactly one statistics entry per request: success at exhaustion,
        failure on a model error OR an abandoned stream (consumer cancel /
        GC closes the generator mid-flight).  The busy span covers only the
        model's production time (each next() + render), never the suspension
        at yield — a slow-reading client must not inflate the duty cycle."""
        recorded = False
        # Triton's decoupled completion protocol: every response carries
        # triton_final_response=false; when the request set
        # triton_enable_empty_final_response, the stream ends with one
        # extra EMPTY response marked triton_final_response=true so the
        # client can detect completion without model-specific EOS logic.
        want_final = bool(params.get("triton_enable_empty_final_response"))
        # Decoupled models bypass the front door, so the tenant identity
        # (x-tenant-id) reaches them through the RESERVED __tenant__
        # parameter on a COPY of the request params — stamped by the
        # engine, never trusted from the client (a spoofed value would
        # let one tenant bill its decode lanes to another).
        params = dict(params)
        params.pop("__tenant__", None)
        if tenant:
            params["__tenant__"] = tenant
        try:
            gen = model.fn(inputs, params, context)
            while True:
                self.busy.begin()
                try:
                    try:
                        # the model's production step runs under the
                        # request trace: generator bodies execute at
                        # next(), often on the CONSUMER's thread, so the
                        # engine's execute() bracket no longer covers
                        # them — an LM submit's fleet prefix lookup
                        # records its child span because of this push
                        # (untraced streams skip the thread-local)
                        if trace is None:
                            partial = next(gen)
                        else:
                            with push_trace(trace):
                                partial = next(gen)
                    except StopIteration:
                        break
                    rendered = self._render_response(
                        model, model_version, request, partial
                    )
                    # merge, don't overwrite: the model (via the reserved
                    # "__parameters__" result key) or the render step may
                    # have set response-level parameters of its own
                    rendered[0].setdefault("parameters", {})[
                        "triton_final_response"
                    ] = False
                finally:
                    self.busy.end()
                yield rendered
            if want_final:
                final = {
                    "model_name": model.name,
                    "model_version": model_version or model.versions[-1],
                    "outputs": [],
                    "parameters": {"triton_final_response": True},
                }
                if request.get("id"):
                    final["id"] = request["id"]
                yield final, []
            t1 = time.monotonic_ns()
            if trace is not None:
                trace.event("COMPUTE_END")
            stats.record(True, t1 - t0, t1 - t_in1, t_in1 - t_in0, 0)
            recorded = True
        except InferenceServerException:
            stats.record(False, time.monotonic_ns() - t0, 0, 0, 0)
            recorded = True
            raise
        except Exception as e:
            stats.record(False, time.monotonic_ns() - t0, 0, 0, 0)
            recorded = True
            raise InferenceServerException(
                f"{model.name}: execution failed: {e}",
                status="500", debug_details=e,
            ) from e
        finally:
            if not recorded:  # abandoned mid-stream (GeneratorExit/GC)
                stats.record(False, time.monotonic_ns() - t0, 0, 0, 0)

    def _pipeline_runner(self):
        """The engine's ensemble DAG scheduler (one per engine, stateless
        across requests — see serve/pipeline.PipelineRunner)."""
        runner = self._pipeline
        if runner is None:
            from client_tpu.serve.pipeline import PipelineRunner

            runner = PipelineRunner(self)
            self._pipeline = runner
        return runner

    def _batcher_for(self, model):
        with self._lock:
            batcher = self._batchers.get(model.name)
            if batcher is None:
                from client_tpu.serve.dynamic_batcher import ModelBatcher

                batcher = ModelBatcher(
                    model,
                    self._stats[model.name],
                    max_queue_delay_s=model.max_queue_delay_us / 1e6,
                    busy=self.busy,
                    max_queue_depth=model.max_queue_depth,
                    registry=self.metrics,
                    prof=self.prof,
                )
                self._batchers[model.name] = batcher
            return batcher

    def _sequence_context(self, params):
        seq_id = params.get("sequence_id", 0)
        if not seq_id:
            return None
        durable = bool(params.get("sequence_durable"))
        ctx = self._sequence_context_local(seq_id, params, durable)
        if ctx is not None:
            return ctx
        # Local miss mid-sequence with a fleet tier attached: the replica
        # that held this sequence may have died, and its replicated
        # snapshot lives in the tier.  The peer RPC runs on the REQUEST
        # thread with no engine lock held (the PEER-CALL-UNDER-LOCK
        # shape) and is bounded by the tier's fan-out x timeout.
        snapshot = None
        fleet = self.fleet
        if fleet is not None:
            lookup = getattr(fleet, "sequence_lookup", None)
            if lookup is not None:
                snapshot = lookup(seq_id)
        return self._install_sequence(seq_id, params, durable,
                                             snapshot)

    def _sequence_context_local(self, seq_id, params, durable):
        """Fast path under the lock: the context when it exists locally
        (or must be created fresh), None when a fleet recovery attempt
        should run first."""
        now = time.monotonic()
        with self._lock:
            # Expire sequences idle past the advertised
            # max_sequence_idle_microseconds so abandoned sequences (client
            # crashed before sequence_end) don't leak state forever.
            expired = [
                sid
                for sid, ctx in self._sequences.items()
                if now - ctx.last_used > self.max_sequence_idle_s
            ]
            for sid in expired:
                del self._sequences[sid]
            missing = seq_id not in self._sequences
            if missing and not params.get("sequence_start") \
                    and self.fleet is not None:
                return None  # try the tier before forking fresh state
            if params.get("sequence_start") or missing:
                self._sequences[seq_id] = SequenceContext(
                    seq_id, durable=durable
                )
            ctx = self._sequences[seq_id]
            ctx.durable = ctx.durable or durable
            ctx.last_used = now
            if params.get("sequence_end"):
                self._sequences.pop(seq_id, None)
            return ctx

    def _install_sequence(self, seq_id, params, durable, snapshot):
        """Install the recovered (or fresh) context after a fleet lookup.
        A context another thread installed meanwhile wins unless the
        snapshot is strictly newer — replication must never move a
        sequence backwards."""
        resumed = False
        with self._lock:
            ctx = self._sequences.get(seq_id)
            if snapshot is not None and (
                ctx is None
                or (float(snapshot.get("epoch", 0.0)),
                    int(snapshot.get("step", 0))) > (ctx.epoch, ctx.step)
            ):
                ctx = SequenceContext.restore(snapshot)
                resumed = True
                self.metrics.inc(
                    "ctpu_fleet_seq_resumes_total",
                    help_=FLEET_HELP["ctpu_fleet_seq_resumes_total"],
                )
            elif ctx is None:
                if durable:
                    # a DURABLE mid-sequence request whose snapshot is
                    # nowhere in the fleet must fail LOUDLY: executing
                    # against a silently forked fresh context would
                    # return wrong answers with no error — the exact
                    # state split SequenceRestartError exists to prevent
                    raise InferenceServerException(
                        f"durable sequence {seq_id!r} has no local state "
                        "and no replicated snapshot in the fleet — its "
                        "replica died before any step was replicated; "
                        "restart the sequence (sequence_start=True)",
                        status="409",
                    )
                ctx = SequenceContext(seq_id, durable=durable)
            ctx.durable = ctx.durable or durable
            ctx.last_used = time.monotonic()
            self._sequences[seq_id] = ctx
            if params.get("sequence_end"):
                self._sequences.pop(seq_id, None)
        if resumed:
            # record the resume AFTER releasing the engine lock (span
            # completion may flush to the trace file).  The marker span
            # CONTINUES the dead replica's trace id (the snapshot's
            # traceparent); the current request's own trace is tagged so
            # both directions of the join are explicit in traceview.
            trace = current_trace()
            span = self.tracer.resume_span(
                ctx.trace_ctx, seq_id, step=ctx.step,
                resumed_by=(trace.trace_id if trace is not None else ""),
            )
            if trace is not None:
                trace.event("SEQ_RESUME")
                trace.tags["resumed_sequence"] = seq_id
                if span is not None:
                    trace.tags["resumed_trace"] = span.trace_id
            self.flight.note(
                "seq_resume", sequence_id=seq_id, step=ctx.step,
                trace=ctx.trace_ctx,
            )
        return ctx

    def _sequence_replay(self, context, params, request):
        """Idempotent duplicate-step short-circuit.

        Requests may declare a monotonic ``sequence_step`` parameter
        (1-based).  A declared step the context already applied returns
        the retained rendering re-stamped with this request's id — the
        retried step after a failover lands exactly once, never twice.
        A declared step AHEAD of the applied counter means intermediate
        steps were lost (a non-durable sequence resumed from a stale
        snapshot): that is the state fork ``SequenceRestartError``
        exists to prevent, so it is rejected with a restartable 409.
        Returns None when the step is fresh and must execute."""
        declared = params.get("sequence_step")
        if not declared:
            return None
        declared = int(declared)
        with self._lock:
            step = context.step
            last = context.last_response
        if declared > step + 1:
            # The client saw step declared-1 acked somewhere, so this
            # context is provably stale (a failover resumed from an old
            # snapshot while the newest one was briefly unreachable).
            # Re-look the fleet up, bounded, before declaring a fork.
            step, last = self._heal_seq_gap(context, declared)
        if declared == step + 1:
            return None  # the expected next step: apply it
        if declared > step:
            raise InferenceServerException(
                f"sequence {context.sequence_id}: declared step {declared} "
                f"skips ahead of the applied counter ({step}) — "
                "intermediate steps were never applied here; restart the "
                "sequence (sequence_start=True)",
                status="409",
            )
        if last is not None and last[0] == declared:
            self._retry_seq_quorum(context)
            response, blobs = last[1], last[2]
            return _stamp_id(response, request), list(blobs)
        raise InferenceServerException(
            f"sequence {context.sequence_id}: step {declared} was already "
            f"applied (counter at {step}) and its response is no longer "
            "retained",
            status="409",
        )

    def _heal_seq_gap(self, context, declared, timeout_s=2.0):
        """Bounded fleet re-lookup when a declared step skips ahead of
        the applied counter.  A declared step N means the client holds
        an ack for step N-1, so a counter below N-1 is not a client
        bug — it is this replica resuming from a stale snapshot while
        the replica (or peer copy) holding the newest one was briefly
        unreachable.  Retrying the lookup for a short window turns that
        transient miss into a clean resume; only when the window closes
        without finding step >= N-1 does the caller raise the
        restartable 409 (the snapshot really is gone).  Peer RPCs run
        with no engine lock held.  Returns the refreshed
        ``(step, last_response)`` pair."""
        fleet = self.fleet
        with self._lock:
            durable = context.durable
            step, last = context.step, context.last_response
        lookup = getattr(fleet, "sequence_lookup", None)
        if lookup is None or not durable:
            return step, last
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                snapshot = lookup(context.sequence_id)
            except Exception:  # pragma: no cover - defensive
                snapshot = None
            if snapshot is not None:
                fresh = SequenceContext.restore(snapshot)
                with self._lock:
                    if (fresh.epoch, fresh.step) > (
                        context.epoch, context.step
                    ):
                        context.step = fresh.step
                        context.epoch = fresh.epoch
                        context.state = fresh.state
                        context.last_response = fresh.last_response
                        context.trace_ctx = fresh.trace_ctx
                    step, last = context.step, context.last_response
                if step + 1 >= declared:
                    self.metrics.inc(
                        "ctpu_fleet_seq_heals_total",
                        help_=FLEET_HELP["ctpu_fleet_seq_heals_total"],
                    )
                    return step, last
            if time.monotonic() >= deadline:
                return step, last
            time.sleep(0.05)

    def _retry_seq_quorum(self, context):
        """Replay-path half of the quorum gate: a retried step whose
        original commit was refused for quorum deficit re-attempts the
        publish before the retained rendering is released.  Success
        clears the deficit (the retry acks 200, now quorum-durable);
        another shortfall refuses again, so no response ever reaches the
        wire without its snapshot at quorum.  No-op when the context is
        not in deficit — the common replay path costs one flag read."""
        with self._lock:
            deficit = context.quorum_deficit
        if not deficit:
            return
        fleet = self.fleet
        if fleet is None or not context.durable:
            return
        acked = fleet.publish_sequence(context.export())
        self._enforce_seq_quorum(fleet, context, acked)

    def _sequence_commit(self, context, params, rendered):
        """Advance the applied-step counter, retain the rendering for
        idempotent replay, and — for durable sequences with a fleet tier
        attached — push the snapshot to peer replicas BEFORE the response
        reaches the wire: an acked step must survive this replica's
        unplanned death.  The peer push runs with no engine lock held and
        is bounded by the tier's fan-out x timeout x per-peer breakers
        (an unreachable fleet degrades to local-only durability)."""
        response, blobs = rendered
        ended = bool(params.get("sequence_end"))
        trace = current_trace()
        with self._lock:
            context.step += 1
            context.last_response = (
                context.step, _strip_id(response), list(blobs),
            )
            if trace is not None:
                # the snapshot carries the committing request's trace
                # context: a survivor resuming this sequence after our
                # death continues the SAME trace id (resume_span)
                context.trace_ctx = trace.traceparent()
        fleet = self.fleet
        if fleet is None or not context.durable:
            return
        if not ended:
            # export OUTSIDE the engine lock: encoding multi-MB numpy
            # state under the repository-wide _lock would stall every
            # concurrent admission.  Steps of ONE sequence are serial by
            # contract, so the context is stable while we encode.
            acked = fleet.publish_sequence(context.export())
            self._enforce_seq_quorum(fleet, context, acked)
        else:
            # the sequence is complete: peers can drop their snapshots
            fleet.forget_sequence(context.sequence_id)

    def _enforce_seq_quorum(self, fleet, context, acked):
        """Quorum gate for a durable step's ack.

        Under ``quorum="majority"`` a step whose snapshot reached fewer
        than ceil((K+1)/2) peers must NOT ack: the step stays applied
        locally (with its retained rendering), the context is flagged
        ``quorum_deficit``, and the client gets a retryable 503 carrying
        breaker evidence.  The retry declares the SAME ``sequence_step``;
        the idempotent replay path re-attempts the publish and only
        releases the retained rendering once quorum is met — so a 200
        always implies the snapshot is quorum-durable, and the model
        never re-applies the step (exactly-once holds).  If this replica
        dies while in deficit, the step was never acked, so losing it is
        a correct (unacked) loss, not acks-then-loses."""
        required = fleet.seq_quorum_required()
        if required <= 0:
            return
        ok = acked >= required
        fleet.note_quorum(ok)
        with self._lock:
            context.quorum_deficit = not ok
        if ok:
            return
        evidence = fleet.quorum_evidence()
        raise InferenceServerException(
            f"sequence {context.sequence_id} step {context.step}: write "
            f"quorum unreachable ({acked}/{required} peer acks, "
            f"replicate_k={fleet.replicate_k}); step applied locally but "
            "not acked — retry the same sequence_step "
            f"(open breakers: {evidence or 'none'})",
            status="503",
        )

    def export_sequence(self, seq_id):
        """One live sequence's snapshot (the fleet tier's ``seq_get``
        handler reads this so a survivor can pull live state during a
        planned handoff), or None.  The encode runs OUTSIDE the
        engine-wide lock (see _sequence_commit) — only the context
        reference is taken under it."""
        with self._lock:
            ctx = self._sequences.get(seq_id)
        return ctx.export() if ctx is not None else None

    def export_sequences(self):
        """Snapshots of every live sequence (the planned-drain export).
        Encoding runs outside the lock; by drain time no request is
        mutating these contexts."""
        with self._lock:
            contexts = list(self._sequences.values())
        return [ctx.export() for ctx in contexts]

    def pressure(self):
        """Autoscaling signal: queued + in-flight work on this replica.
        Gossiped on fleet probes (``FleetTier.local_summary``) and
        surfaced per-endpoint through ``EndpointPool.pressures()``."""
        with self._flight_cv:
            inflight = self._inflight
        with self._lock:
            batchers = list(self._batchers.values())
        depth = 0
        for batcher in batchers:
            try:
                depth += batcher.queue_depth()
            except Exception:  # pragma: no cover - defensive
                pass
        return {"queue_depth": depth + inflight, "inflight": inflight}

    def _gather_inputs(self, model, request, binary_section):
        """Resolve request inputs to arrays.

        *binary_section* is either one contiguous bytes object (the HTTP
        binary extension: tensors back-to-back after the JSON header) or a
        list of per-tensor buffers (the gRPC frontend hands over the proto's
        ``raw_input_contents`` untouched).  Both decode through zero-copy
        ``np.frombuffer`` views — no tensor bytes are copied between the
        transport and the model.
        """
        specs = {t.name: t for t in model.inputs}
        arrays = {}
        offset = 0
        part_cursor = 0
        sectioned = not isinstance(binary_section, (list, tuple))
        for entry in request.get("inputs", []):
            name = entry["name"]
            spec = specs.get(name)
            if spec is None:
                raise InferenceServerException(
                    f"unexpected inference input '{name}' for model "
                    f"'{model.name}'",
                    status="400",
                )
            shape = entry["shape"]
            datatype = entry["datatype"]
            if spec.datatype != datatype:
                raise InferenceServerException(
                    f"inference input '{name}' data-type is '{datatype}', but "
                    f"model expects '{spec.datatype}'",
                    status="400",
                )
            params = entry.get("parameters", {}) or {}
            if "shared_memory_region" in params:
                arrays[name] = self.shm.read_tensor(
                    params["shared_memory_region"],
                    params.get("shared_memory_offset", 0),
                    params["shared_memory_byte_size"],
                    datatype,
                    shape,
                )
            elif "binary_data_size" in params:
                size = params["binary_data_size"]
                if sectioned:
                    raw = memoryview(binary_section)[offset : offset + size]
                    offset += size
                else:
                    if part_cursor >= len(binary_section):
                        raise InferenceServerException(
                            f"input '{name}' binary section underrun",
                            status="400",
                        )
                    raw = binary_section[part_cursor]
                    part_cursor += 1
                if len(raw) != size:
                    raise InferenceServerException(
                        f"input '{name}' binary section underrun", status="400"
                    )
                arrays[name] = from_wire_bytes(raw, datatype, shape)
            elif "data" in entry:
                arrays[name] = _np_from_json_data(entry["data"], datatype, shape)
            else:
                raise InferenceServerException(
                    f"input '{name}' has no data", status="400"
                )
        missing = [
            t.name for t in model.inputs if t.name not in arrays and not t.optional
        ]
        if missing:
            raise InferenceServerException(
                f"expected {len(model.inputs)} inputs but got "
                f"{len(arrays)} inputs for model '{model.name}' "
                f"(missing {missing})",
                status="400",
            )
        return arrays

    def _render_response(self, model, model_version, request, result_arrays):
        requested = request.get("outputs")
        req_params = request.get("parameters", {}) or {}
        specs = {t.name: t for t in model.outputs}
        if requested:
            selection = [(o["name"], o.get("parameters", {}) or {}) for o in requested]
        else:
            default_binary = bool(req_params.get("binary_data_output"))
            selection = [
                (t.name, {"binary_data": default_binary}) for t in model.outputs
            ]

        outputs_json = []
        blobs = []
        for name, params in selection:
            if name == "__parameters__" or name not in result_arrays:
                raise InferenceServerException(
                    f"unexpected inference output '{name}' for model "
                    f"'{model.name}'",
                    status="400",
                )
            # keep the model's output device-resident until the disposition is
            # known — the TPU-shm path never needs a D2H transfer; outputs
            # without array protocol (lists, scalars) normalize host-side
            arr = result_arrays[name]
            if not hasattr(arr, "dtype"):
                arr = np.asarray(arr)
            spec = specs.get(name)
            datatype = (
                spec.datatype if spec is not None else _np_dtype_to_wire(arr)
            )
            class_count = params.get("classification", 0)
            if class_count:
                arr = _classify(
                    np.asarray(arr), class_count, spec.labels if spec else []
                )
                datatype = "BYTES"
            entry = {
                "name": name,
                "datatype": datatype,
                "shape": list(arr.shape),
            }
            if "shared_memory_region" in params:
                written = self.shm.write_tensor(
                    params["shared_memory_region"],
                    params.get("shared_memory_offset", 0),
                    arr,
                    datatype,
                    params["shared_memory_byte_size"],
                )
                entry["parameters"] = {
                    "shared_memory_region": params["shared_memory_region"],
                    "shared_memory_byte_size": written,
                }
            elif params.get("binary_data", False):
                raw = to_wire_bytes(np.asarray(arr), datatype)
                entry["parameters"] = {"binary_data_size": len(raw)}
                blobs.append(raw)
            else:
                host = np.asarray(arr)
                if datatype == "BYTES":
                    entry["data"] = [
                        v.decode("utf-8", errors="replace")
                        if isinstance(v, bytes)
                        else str(v)
                        for v in host.flatten()
                    ]
                else:
                    entry["data"] = [v.item() for v in host.flatten()]
            outputs_json.append(entry)

        response = {
            "model_name": model.name,
            "model_version": model_version or model.versions[-1],
            "outputs": outputs_json,
        }
        # reserved result key: a model sets response-level parameters by
        # including "__parameters__": {...} beside its output tensors
        # (never selected as a tensor above; both servers forward them).
        # Not available to fused_batching models: their fn is traced, so
        # the dict would be a trace-time constant (the fused path drops it)
        extra_params = result_arrays.get("__parameters__")
        if extra_params:
            response["parameters"] = dict(extra_params)
        if request.get("id"):
            response["id"] = request["id"]
        return response, blobs

    def close(self):
        with self._lock:
            batchers = list(self._batchers.values())
            self._batchers.clear()
            models = list(self._models.values())
        for batcher in batchers:
            batcher.close()
        # model-owned resources (e.g. the continuous-batching scheduler's
        # thread + device cache) release with the engine, not the process
        for model in models:
            closer = getattr(model, "closer", None)
            if closer is not None:
                try:
                    closer()
                except Exception:
                    pass
        self._busy_observer.close()
        self.tracer.flush()  # buffered trace records reach trace_file
        self.shm.close()


def _batchable_request(model, inputs, params, context, request):
    from client_tpu.serve.dynamic_batcher import batchable_request

    return batchable_request(model, inputs, params, context, request)


def _strip_id(response):
    """The id-less rendering shared via cache/coalescing (the request id is
    caller identity, not content; every reader stamps its own)."""
    if "id" in response:
        return {k: v for k, v in response.items() if k != "id"}
    return response


def _stamp_id(response, request):
    """A shallow per-caller copy of a shared response with this request's
    id (nested structures stay shared — readers only serialize them)."""
    out = dict(response)
    if request.get("id"):
        out["id"] = request["id"]
    return out


def _np_dtype_to_wire(arr):
    from client_tpu.utils import np_to_triton_dtype

    dt = np_to_triton_dtype(arr.dtype)
    if dt is None:
        raise InferenceServerException(
            f"model returned unsupported dtype {arr.dtype}", status="500"
        )
    return dt


def _batch_of(model, request):
    if model.max_batch_size <= 0:
        return 1
    inputs = request.get("inputs", [])
    if inputs and inputs[0].get("shape"):
        return int(inputs[0]["shape"][0])
    return 1


def _classify(arr, class_count, labels):
    """Classification extension: top-N "score:index[:label]" BYTES strings."""
    def topk_strings(vec):
        k = min(class_count, vec.size)
        idx = np.argsort(vec)[::-1][:k]
        out = []
        for i in idx:
            s = f"{float(vec[i]):f}:{int(i)}"
            if labels and int(i) < len(labels):
                s += f":{labels[int(i)]}"
            out.append(s.encode("utf-8"))
        return out

    if arr.ndim <= 1:
        return np.array(topk_strings(np.atleast_1d(arr)), dtype=np.object_)
    flat = arr.reshape(arr.shape[0], -1)
    rows = [topk_strings(row) for row in flat]
    return np.array(rows, dtype=np.object_)
