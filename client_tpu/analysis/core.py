"""tpu-lint core: findings, the rule registry, suppressions, file driver.

Pure stdlib (``ast`` + ``tokenize``-free regex comments) so the analyzer
runs in any environment the repo does — no jax, no numpy, no third-party
lint framework.  Each rule encodes an invariant this codebase has actually
shipped a bug against; see ``rules.py`` for the catalog and README
"Static analysis (tpu-lint)" for the rationale per rule.
"""

import ast
import dataclasses
import os
import re

# ``# tpulint: disable=RULE-A,RULE-B`` or a bare ``# tpulint: disable``
# (all rules).  On a code line it suppresses that line; on a comment-only
# line it suppresses the line below (so a rationale can sit above the
# statement it excuses).
_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable(?:=(?P<rules>[A-Za-z0-9_,\- ]+))?"
)
_ALL = "*"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str  # stripped source line: the baseline's drift-stable key

    def key(self):
        """Baseline identity: stable across pure line-number drift."""
        return (self.path, self.rule, self.snippet)

    def to_dict(self):
        return dataclasses.asdict(self)

    def render(self):
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule} {self.message}"
        )


class Rule:
    """Base class: subclasses set ``id``/``rationale`` and implement
    ``check(tree, lines, path) -> iterable[Finding]``."""

    id = ""
    rationale = ""

    def finding(self, path, lines, node, message):
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        snippet = ""
        if 1 <= line <= len(lines):
            snippet = lines[line - 1].strip()
        return Finding(self.id, path, line, col, message, snippet)

    def check(self, tree, lines, path):  # pragma: no cover - interface
        raise NotImplementedError


REGISTRY = {}


def register(cls):
    """Class decorator adding a rule to the global registry."""
    REGISTRY[cls.id] = cls()
    return cls


def parse_suppressions(lines):
    """Map line number -> set of suppressed rule ids ('*' = all)."""
    out = {}
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = m.group("rules")
        ids = (
            {_ALL}
            if not rules
            else {r.strip().upper() for r in rules.split(",") if r.strip()}
        )
        target = i
        if text.lstrip().startswith("#"):
            target = i + 1  # comment-only line covers the next line
        out.setdefault(target, set()).update(ids)
        out.setdefault(i, set()).update(ids)
    return out


def scan_source(source, path, rules=None):
    """Run every (or the given) rule over one file's source text."""
    active = list((rules if rules is not None else REGISTRY).values())
    lines = source.splitlines()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Finding(
                "PARSE-ERROR", path, e.lineno or 1, e.offset or 0,
                f"could not parse: {e.msg}", "",
            )
        ]
    suppressed = parse_suppressions(lines)
    findings = []
    reported = set()  # one finding per (rule, line): passes can overlap
    for rule in active:
        for f in rule.check(tree, lines, path):
            ids = suppressed.get(f.line, ())
            if _ALL in ids or f.rule.upper() in ids:
                continue
            if (f.rule, f.line) in reported:
                continue
            reported.add((f.rule, f.line))
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_python_files(paths, exclude_parts=("analysis_fixtures",)):
    """Yield .py files under the given files/directories, skipping any
    whose path contains an excluded component (lint fixtures hold
    intentional violations)."""
    seen = set()
    for root in paths:
        if os.path.isfile(root):
            # an explicitly named file is always scanned — the exclusion
            # only guards directory walks (fixtures hold intentional
            # violations but must be scannable on demand)
            norm = os.path.normpath(root)
            if norm not in seen:
                seen.add(norm)
                yield norm
            continue
        # exclusion applies BELOW the named root only (the dirnames
        # pruning): explicitly passing an excluded directory (e.g. the
        # fixtures) scans it — same no-silent-green principle as the
        # missing-path CLI error
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in exclude_parts and d != "__pycache__"
            )
            for f in sorted(filenames):
                if not f.endswith(".py"):
                    continue
                norm = os.path.normpath(os.path.join(dirpath, f))
                if norm in seen:
                    continue
                seen.add(norm)
                yield norm


def scan_paths(paths, rules=None, exclude_parts=("analysis_fixtures",)):
    findings = []
    for path in iter_python_files(paths, exclude_parts):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            findings.append(
                Finding("READ-ERROR", path, 1, 0, f"unreadable: {e}", "")
            )
            continue
        findings.extend(scan_source(source, path, rules))
    return findings
