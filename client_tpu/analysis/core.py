"""tpu-lint core: findings, the rule registries, suppressions, file driver.

Pure stdlib (``ast`` + regex comments) so the analyzer runs in any
environment the repo does — no jax, no numpy, no third-party lint
framework.  Two rule families share one driver:

- **per-file rules** (``rules.py``): one function/file at a time;
- **program rules** (``concurrency.py``): run over the whole-program call
  graph + lock summaries built by ``callgraph.py`` — interprocedural
  hazards (lock-order inversion, blocking/callbacks reached under a lock
  through any call depth) that no single-file pass can see.

Each rule encodes an invariant this codebase has actually shipped a bug
against; see the rule catalogs and README "Static analysis" for the
rationale per rule.

Suppressions require a reason: ``# tpulint: disable=RULE -- why``.  A
bare ``# tpulint: disable`` (or one without the ``-- why`` tail) is
itself a finding (BARE-SUPPRESS) — a waiver nobody can audit is debt,
not a decision.
"""

import ast
import dataclasses
import io
import os
import re
import tokenize

# A suppression directive must BE the comment, not merely appear inside
# one (anchored match): prose quoting the syntax — like this very
# paragraph would if it spelled the directive unquoted at a comment
# start — is neither a waiver nor a STALE-SUPPRESS finding.  Forms:
# the directive with ``=RULE-A,RULE-B`` plus a ``-- reason`` tail, or
# reason + no rule list (all rules).  On a code line it suppresses that
# line; on a comment-only line it suppresses the line below (so a
# rationale can sit above the statement it excuses).  The ``-- reason``
# tail is mandatory: reason-less suppressions become BARE-SUPPRESS
# findings, and reasoned ones whose rule no longer fires on the line
# become STALE-SUPPRESS findings.
_SUPPRESS_RE = re.compile(r"^#+\s*tpulint:\s*disable(?P<tail>.*)")
_ALL = "*"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str  # stripped source line: the baseline's drift-stable key

    def key(self):
        """Baseline identity: stable across pure line-number drift."""
        return (self.path, self.rule, self.snippet)

    def to_dict(self):
        return dataclasses.asdict(self)

    def render(self):
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule} {self.message}"
        )


class Rule:
    """Per-file rule base: subclasses set ``id``/``rationale`` and
    implement ``check(tree, lines, path) -> iterable[Finding]``."""

    id = ""
    rationale = ""

    def finding(self, path, lines, node, message):
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        snippet = ""
        if 1 <= line <= len(lines):
            snippet = lines[line - 1].strip()
        return Finding(self.id, path, line, col, message, snippet)

    def check(self, tree, lines, path):  # pragma: no cover - interface
        raise NotImplementedError


class ProgramRule:
    """Whole-program rule base: subclasses implement
    ``check_program(program) -> iterable[Finding]`` over a
    :class:`client_tpu.analysis.callgraph.Program`.  Snippets are filled
    in and suppressions applied by the driver."""

    id = ""
    rationale = ""

    def check_program(self, program):  # pragma: no cover - interface
        raise NotImplementedError


REGISTRY = {}
PROGRAM_REGISTRY = {}


def register(cls):
    """Class decorator adding a per-file rule to the global registry."""
    REGISTRY[cls.id] = cls()
    return cls


def register_program(cls):
    """Class decorator adding a whole-program rule to the registry."""
    PROGRAM_REGISTRY[cls.id] = cls()
    return cls


def all_rules():
    """{id: rule} over both families (catalog/--explain/--rules)."""
    merged = dict(REGISTRY)
    merged.update(PROGRAM_REGISTRY)
    return merged


def _comment_tokens(lines):
    """(line, column, text) for every real COMMENT token — tokenizing
    (rather than regexing lines) keeps docstrings and string literals
    that merely *mention* the suppression syntax from acting as (or being
    flagged as) suppressions."""
    source = "\n".join(lines) + "\n"
    out = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # unparsable tail: fall back to line-level matching so a file the
        # AST pass already rejects still reports its suppressions sanely
        for i, text in enumerate(lines, start=1):
            idx = text.find("#")
            if idx >= 0:
                out.append((i, idx, text[idx:]))
    return out


def parse_suppressions(lines):
    """Parse suppression comments.

    Returns ``(by_line, bare, comments)``: *by_line* maps line number ->
    set of suppressed rule ids ('*' = all), *bare* lists ``(line, ids)``
    for suppressions missing the mandatory ``-- reason`` tail, and
    *comments* records every suppression comment individually
    (``{"line", "covers", "ids", "bare"}``) so the STALE-SUPPRESS pass
    can audit each waiver against what actually fired on its lines.
    """
    out = {}
    bare = []
    comments = []
    for i, col, comment in _comment_tokens(lines):
        m = _SUPPRESS_RE.search(comment)
        if not m:
            continue
        tail = m.group("tail") or ""
        spec, sep, reason = tail.partition("--")
        spec = spec.strip()
        if spec.startswith("="):
            ids = {
                r.strip().upper()
                for r in spec[1:].split(",")
                if r.strip()
            }
        else:
            ids = {_ALL}
        is_bare = not sep or not reason.strip()
        if is_bare:
            bare.append((i, ids))
        target = i
        if not lines[i - 1][:col].strip():
            target = i + 1  # comment-only line covers the next line
        out.setdefault(target, set()).update(ids)
        out.setdefault(i, set()).update(ids)
        comments.append({
            "line": i, "covers": sorted({i, target}),
            "ids": sorted(ids), "bare": is_bare,
        })
    return out, bare, comments


def _suppressed(finding, by_line):
    if finding.rule in ("BARE-SUPPRESS", "STALE-SUPPRESS"):
        # a waiver cannot waive the rules about waivers
        return False
    ids = by_line.get(finding.line, ())
    return _ALL in ids or finding.rule.upper() in ids


def scan_source(source, path, rules=None, tree=None, parsed_suppressions=None,
                suppressed_out=None):
    """Run every (or the given) per-file rule over one file's source.

    *tree* / *parsed_suppressions* accept precomputed results so a driver
    that also needs them (``_analyze_file`` builds the callgraph summary
    from the same tree) parses and tokenizes each file exactly once.
    *suppressed_out*, when given a list, receives the findings a
    suppression comment filtered — the STALE-SUPPRESS pass audits
    waivers against them.
    """
    active = list((rules if rules is not None else REGISTRY).values())
    lines = source.splitlines()
    if tree is None:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            return [
                Finding(
                    "PARSE-ERROR", path, e.lineno or 1, e.offset or 0,
                    f"could not parse: {e.msg}", "",
                )
            ]
    if parsed_suppressions is None:
        parsed_suppressions = parse_suppressions(lines)
    suppressed, bare, _comments = parsed_suppressions
    findings = []
    reported = set()  # one finding per (rule, line): passes can overlap
    for rule in active:
        if hasattr(rule, "check_parsed"):
            found = rule.check_parsed(bare, lines, path)
        else:
            found = rule.check(tree, lines, path)
        for f in found:
            if _suppressed(f, suppressed):
                if suppressed_out is not None:
                    suppressed_out.append(f)
                continue
            if (f.rule, f.line) in reported:
                continue
            reported.add((f.rule, f.line))
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_python_files(paths, exclude_parts=("analysis_fixtures",)):
    """Yield .py files under the given files/directories, skipping any
    whose path contains an excluded component (lint fixtures hold
    intentional violations)."""
    seen = set()
    for root in paths:
        if os.path.isfile(root):
            # an explicitly named file is always scanned — the exclusion
            # only guards directory walks (fixtures hold intentional
            # violations but must be scannable on demand)
            norm = os.path.normpath(root)
            if norm not in seen:
                seen.add(norm)
                yield norm
            continue
        # exclusion applies BELOW the named root only (the dirnames
        # pruning): explicitly passing an excluded directory (e.g. the
        # fixtures) scans it — same no-silent-green principle as the
        # missing-path CLI error
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in exclude_parts and d != "__pycache__"
            )
            for f in sorted(filenames):
                if not f.endswith(".py"):
                    continue
                norm = os.path.normpath(os.path.join(dirpath, f))
                if norm in seen:
                    continue
                seen.add(norm)
                yield norm


def _analyze_file(source, path, rules):
    """(findings, summary, suppression-map, comments, suppressed-hits)
    for one file.

    *summary* is None on parse errors (the PARSE-ERROR finding carries
    the news; program rules skip the file).  The file is parsed and
    tokenized exactly once, shared between the per-file rules and the
    callgraph summary.  *comments* are the parsed suppression comments;
    *suppressed-hits* lists ``(rule, line)`` for every per-file finding
    a suppression filtered (STALE-SUPPRESS input).
    """
    from client_tpu.analysis import callgraph

    lines = source.splitlines()
    by_line, bare, comments = parse_suppressions(lines)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return scan_source(source, path, rules), None, by_line, [], []
    suppressed_hits = []
    findings = scan_source(
        source, path, rules, tree=tree,
        parsed_suppressions=(by_line, bare, comments),
        suppressed_out=suppressed_hits,
    )
    summary = callgraph.summarize_module(tree, path)
    return (
        findings, summary, by_line, comments,
        [(f.rule, f.line) for f in suppressed_hits],
    )


def scan_paths(paths, rules=None, exclude_parts=("analysis_fixtures",),
               cache=None, program_rules=None):
    """Scan files and the program they form.

    ``rules``/``program_rules``: None = all registered; pass a dict to
    filter (an empty dict disables that family).  ``cache`` is an
    optional :class:`client_tpu.analysis.cache.AnalysisCache` reused
    across runs — only consulted for full-default-rule scans (a filtered
    scan must not poison or be poisoned by cached full results).  On a
    full scan the whole-program pass (program rules + the
    STALE-SUPPRESS audit) is additionally cached under a *fileset
    digest* over every scanned file's stat key: when nothing changed,
    the graph walks are skipped entirely and a warm ``make lint`` stays
    ~a second.
    """
    from client_tpu.analysis import callgraph

    use_cache = cache is not None and rules is None
    findings = []
    summaries = []
    suppress_by_path = {}
    comments_by_path = {}    # path -> (comments, per-file suppressed hits)
    fileset = []             # (path, stat-key) pairs -> program digest
    digest_ok = use_cache
    snippet_lines = {}  # program-finding snippets come from the source
    for path in iter_python_files(paths, exclude_parts):
        entry = cache.get(path) if use_cache else None
        if entry is not None:
            file_findings = [Finding(**f) for f in entry["findings"]]
            summary = (
                callgraph.ModuleSummary.from_dict(entry["summary"])
                if entry["summary"] is not None
                else None
            )
            by_line = {
                int(k): set(v) for k, v in entry["suppress"].items()
            }
            comments = entry.get("comments", [])
            hits = [tuple(h) for h in entry.get("suppressed", [])]
            stat_key = cache.stat_for(path)
        else:
            # stat BEFORE reading: a save landing mid-analysis must leave
            # the entry stale (re-scan next run), never fresh-looking
            stat_key = cache.stat_key(path) if use_cache else None
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    source = fh.read()
            except OSError as e:
                findings.append(
                    Finding("READ-ERROR", path, 1, 0, f"unreadable: {e}", "")
                )
                digest_ok = False
                continue
            file_findings, summary, by_line, comments, hits = (
                _analyze_file(source, path, rules)
            )
            # keep THIS run's lines for program-finding snippets: a save
            # landing mid-run must not produce a snippet (the baseline's
            # drift-stable key) from content nobody analyzed
            snippet_lines[path] = source.splitlines()
            if use_cache:
                cache.put(path, {
                    "findings": [f.to_dict() for f in file_findings],
                    "summary": (
                        summary.to_dict() if summary is not None else None
                    ),
                    "suppress": {
                        str(k): sorted(v) for k, v in by_line.items()
                    },
                    "comments": comments,
                    "suppressed": [list(h) for h in hits],
                }, stat_key)
        if stat_key is None:
            digest_ok = False
        else:
            fileset.append((path, stat_key))
        findings.extend(file_findings)
        if summary is not None:
            summaries.append(summary)
            suppress_by_path[path] = by_line
            comments_by_path[path] = (comments, hits)

    active_program = (
        PROGRAM_REGISTRY if program_rules is None else program_rules
    )
    full_scan = rules is None and program_rules is None
    digest = (
        cache.fileset_digest(fileset)
        if digest_ok and full_scan else None
    )
    cached_program = (
        cache.get_program(digest) if digest is not None else None
    )
    def snippet_at(path, line):
        """Drift-stable baseline snippet for a program/stale finding —
        lazily reading cache-hit files whose source this run never saw."""
        if path not in snippet_lines:
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    snippet_lines[path] = fh.read().splitlines()
            except OSError:
                snippet_lines[path] = []
        lines = snippet_lines[path]
        return lines[line - 1].strip() if 1 <= line <= len(lines) else ""

    if cached_program is not None:
        findings.extend(Finding(**f) for f in cached_program)
    elif (active_program or full_scan) and summaries:
        program_findings = []
        if active_program:
            program = callgraph.build_program(summaries)
            reported = set()
            for rule in active_program.values():
                for f in rule.check_program(program):
                    by_line = suppress_by_path.get(f.path, {})
                    if _suppressed(f, by_line):
                        comments_by_path.get(f.path, ([], []))[1].append(
                            (f.rule, f.line)
                        )
                        continue
                    # message is part of the key: two DISTINCT cycles can
                    # anchor on the same witness line (a call made under
                    # two held locks); only true duplicates may collapse
                    key = (f.rule, f.path, f.line, f.message)
                    if key in reported:
                        continue
                    reported.add(key)
                    program_findings.append(dataclasses.replace(
                        f, snippet=snippet_at(f.path, f.line),
                    ))
        if full_scan:
            # the STALE-SUPPRESS audit needs BOTH rule families' verdicts
            # (a waiver may exist for a program finding), so it runs — and
            # is cached — with the program pass
            stale_rule = REGISTRY.get("STALE-SUPPRESS")
            if stale_rule is not None:
                for path, (comments, hits) in sorted(
                    comments_by_path.items()
                ):
                    for f in stale_rule.check_comments(
                        path, comments, hits
                    ):
                        # the comment line IS the snippet: the baseline
                        # key must tell two stale waivers in one file
                        # apart
                        program_findings.append(dataclasses.replace(
                            f, snippet=snippet_at(f.path, f.line),
                        ))
        if digest is not None:
            cache.put_program(
                digest, [f.to_dict() for f in program_findings]
            )
        findings.extend(program_findings)

    if use_cache:
        cache.save()
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


@register
class BareSuppressRule(Rule):
    """BARE-SUPPRESS — suppression comments without a ``-- reason``.

    Every waiver is a decision someone later has to re-audit; a bare
    ``# tpulint: disable=RULE`` records the decision without the
    reasoning, so the next reader cannot tell a load-bearing exemption
    from a drive-by silence.  The reason rides in the comment itself:
    ``# tpulint: disable=RULE -- why this is safe``.  BARE-SUPPRESS
    findings cannot themselves be suppressed.
    """

    id = "BARE-SUPPRESS"
    rationale = (
        "a suppression without a reason cannot be audited — write "
        "`# tpulint: disable=RULE -- why`"
    )

    def check(self, tree, lines, path):
        _by_line, bare, _comments = parse_suppressions(lines)
        return self.check_parsed(bare, lines, path)

    def check_parsed(self, bare, lines, path):
        """The driver hands over its already-parsed suppressions so the
        file is tokenized once, not once per consumer."""
        findings = []
        for line, ids in bare:
            what = (
                "all rules" if _ALL in ids else ", ".join(sorted(ids))
            )
            snippet = lines[line - 1].strip() if line <= len(lines) else ""
            findings.append(Finding(
                self.id, path, line, 0,
                f"suppression of {what} carries no reason — append "
                "`-- <why this is safe>`", snippet,
            ))
        return findings


@register
class StaleSuppressRule(Rule):
    """STALE-SUPPRESS — a reasoned waiver whose rule no longer fires.

    A ``# tpulint: disable=RULE -- why`` comment on a line where RULE no
    longer produces a finding is debt pointing at code that moved on:
    either the hazard was fixed (delete the waiver) or the code drifted
    out from under it (the waiver now silences NOTHING today and the
    wrong thing tomorrow).  Auditing it automatically keeps the waiver
    set honest as rules and code evolve.

    The audit needs every rule family's verdicts for the file — a waiver
    may exist for a whole-program finding — so the driver computes it
    alongside the program pass on full scans (``scan_paths`` with the
    default rule sets); per-file ``scan_source`` calls and ``--rules``-
    filtered runs never report it (a filtered scan cannot tell unused
    from unchecked).  Blanket waivers (``disable`` with no rule list)
    are stale when NO finding at all was suppressed on their lines.
    Reason-less waivers are BARE-SUPPRESS findings already and are not
    double-reported here.  Like BARE-SUPPRESS, a STALE-SUPPRESS finding
    cannot itself be waived — the fix is deleting the dead comment.
    """

    id = "STALE-SUPPRESS"
    rationale = (
        "a suppression whose rule no longer fires on its line silences "
        "nothing today and the wrong thing tomorrow — delete it"
    )

    def check(self, tree, lines, path):
        return []  # driver-computed on full scans (needs program verdicts)

    def check_comments(self, path, comments, suppressed_hits):
        """*suppressed_hits*: (rule, line) for every finding — per-file
        AND program — that a suppression in this file filtered."""
        hits_by_line = {}
        for rule, line in suppressed_hits:
            hits_by_line.setdefault(line, set()).add(rule.upper())
        findings = []
        for comment in comments:
            if comment["bare"]:
                continue  # already a BARE-SUPPRESS finding
            covered = set()
            for line in comment["covers"]:
                covered |= hits_by_line.get(line, set())
            ids = set(comment["ids"])
            if _ALL in ids:
                stale = sorted(ids) if not covered else []
            else:
                stale = sorted(ids - covered)
            for rule_id in stale:
                what = (
                    "any rule" if rule_id == _ALL else rule_id
                )
                findings.append(Finding(
                    self.id, path, comment["line"], 0,
                    f"suppression of {what} no longer matches a "
                    "finding on its line — the waived hazard is gone "
                    "(or moved); delete the stale comment", "",
                ))
        return findings
