"""tpu-lint core: findings, the rule registries, suppressions, file driver.

Pure stdlib (``ast`` + regex comments) so the analyzer runs in any
environment the repo does — no jax, no numpy, no third-party lint
framework.  Two rule families share one driver:

- **per-file rules** (``rules.py``): one function/file at a time;
- **program rules** (``concurrency.py``): run over the whole-program call
  graph + lock summaries built by ``callgraph.py`` — interprocedural
  hazards (lock-order inversion, blocking/callbacks reached under a lock
  through any call depth) that no single-file pass can see.

Each rule encodes an invariant this codebase has actually shipped a bug
against; see the rule catalogs and README "Static analysis" for the
rationale per rule.

Suppressions require a reason: ``# tpulint: disable=RULE -- why``.  A
bare ``# tpulint: disable`` (or one without the ``-- why`` tail) is
itself a finding (BARE-SUPPRESS) — a waiver nobody can audit is debt,
not a decision.
"""

import ast
import dataclasses
import io
import os
import re
import tokenize

# ``# tpulint: disable=RULE-A,RULE-B -- reason`` or ``# tpulint: disable
# -- reason`` (all rules).  On a code line it suppresses that line; on a
# comment-only line it suppresses the line below (so a rationale can sit
# above the statement it excuses).  The ``-- reason`` tail is mandatory:
# reason-less suppressions become BARE-SUPPRESS findings.
_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable(?P<tail>.*)")
_ALL = "*"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str  # stripped source line: the baseline's drift-stable key

    def key(self):
        """Baseline identity: stable across pure line-number drift."""
        return (self.path, self.rule, self.snippet)

    def to_dict(self):
        return dataclasses.asdict(self)

    def render(self):
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule} {self.message}"
        )


class Rule:
    """Per-file rule base: subclasses set ``id``/``rationale`` and
    implement ``check(tree, lines, path) -> iterable[Finding]``."""

    id = ""
    rationale = ""

    def finding(self, path, lines, node, message):
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        snippet = ""
        if 1 <= line <= len(lines):
            snippet = lines[line - 1].strip()
        return Finding(self.id, path, line, col, message, snippet)

    def check(self, tree, lines, path):  # pragma: no cover - interface
        raise NotImplementedError


class ProgramRule:
    """Whole-program rule base: subclasses implement
    ``check_program(program) -> iterable[Finding]`` over a
    :class:`client_tpu.analysis.callgraph.Program`.  Snippets are filled
    in and suppressions applied by the driver."""

    id = ""
    rationale = ""

    def check_program(self, program):  # pragma: no cover - interface
        raise NotImplementedError


REGISTRY = {}
PROGRAM_REGISTRY = {}


def register(cls):
    """Class decorator adding a per-file rule to the global registry."""
    REGISTRY[cls.id] = cls()
    return cls


def register_program(cls):
    """Class decorator adding a whole-program rule to the registry."""
    PROGRAM_REGISTRY[cls.id] = cls()
    return cls


def all_rules():
    """{id: rule} over both families (catalog/--explain/--rules)."""
    merged = dict(REGISTRY)
    merged.update(PROGRAM_REGISTRY)
    return merged


def _comment_tokens(lines):
    """(line, column, text) for every real COMMENT token — tokenizing
    (rather than regexing lines) keeps docstrings and string literals
    that merely *mention* the suppression syntax from acting as (or being
    flagged as) suppressions."""
    source = "\n".join(lines) + "\n"
    out = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.start[1], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # unparsable tail: fall back to line-level matching so a file the
        # AST pass already rejects still reports its suppressions sanely
        for i, text in enumerate(lines, start=1):
            idx = text.find("#")
            if idx >= 0:
                out.append((i, idx, text[idx:]))
    return out


def parse_suppressions(lines):
    """Parse suppression comments.

    Returns ``(by_line, bare)`` where *by_line* maps line number -> set of
    suppressed rule ids ('*' = all) and *bare* lists ``(line, ids)`` for
    suppressions missing the mandatory ``-- reason`` tail.
    """
    out = {}
    bare = []
    for i, col, comment in _comment_tokens(lines):
        m = _SUPPRESS_RE.search(comment)
        if not m:
            continue
        tail = m.group("tail") or ""
        spec, sep, reason = tail.partition("--")
        spec = spec.strip()
        if spec.startswith("="):
            ids = {
                r.strip().upper()
                for r in spec[1:].split(",")
                if r.strip()
            }
        else:
            ids = {_ALL}
        if not sep or not reason.strip():
            bare.append((i, ids))
        target = i
        if not lines[i - 1][:col].strip():
            target = i + 1  # comment-only line covers the next line
        out.setdefault(target, set()).update(ids)
        out.setdefault(i, set()).update(ids)
    return out, bare


def _suppressed(finding, by_line):
    if finding.rule == "BARE-SUPPRESS":
        # a waiver cannot waive the rule about waivers
        return False
    ids = by_line.get(finding.line, ())
    return _ALL in ids or finding.rule.upper() in ids


def scan_source(source, path, rules=None, tree=None, parsed_suppressions=None):
    """Run every (or the given) per-file rule over one file's source.

    *tree* / *parsed_suppressions* accept precomputed results so a driver
    that also needs them (``_analyze_file`` builds the callgraph summary
    from the same tree) parses and tokenizes each file exactly once.
    """
    active = list((rules if rules is not None else REGISTRY).values())
    lines = source.splitlines()
    if tree is None:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            return [
                Finding(
                    "PARSE-ERROR", path, e.lineno or 1, e.offset or 0,
                    f"could not parse: {e.msg}", "",
                )
            ]
    if parsed_suppressions is None:
        parsed_suppressions = parse_suppressions(lines)
    suppressed, bare = parsed_suppressions
    findings = []
    reported = set()  # one finding per (rule, line): passes can overlap
    for rule in active:
        if hasattr(rule, "check_parsed"):
            found = rule.check_parsed(bare, lines, path)
        else:
            found = rule.check(tree, lines, path)
        for f in found:
            if _suppressed(f, suppressed):
                continue
            if (f.rule, f.line) in reported:
                continue
            reported.add((f.rule, f.line))
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def iter_python_files(paths, exclude_parts=("analysis_fixtures",)):
    """Yield .py files under the given files/directories, skipping any
    whose path contains an excluded component (lint fixtures hold
    intentional violations)."""
    seen = set()
    for root in paths:
        if os.path.isfile(root):
            # an explicitly named file is always scanned — the exclusion
            # only guards directory walks (fixtures hold intentional
            # violations but must be scannable on demand)
            norm = os.path.normpath(root)
            if norm not in seen:
                seen.add(norm)
                yield norm
            continue
        # exclusion applies BELOW the named root only (the dirnames
        # pruning): explicitly passing an excluded directory (e.g. the
        # fixtures) scans it — same no-silent-green principle as the
        # missing-path CLI error
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in exclude_parts and d != "__pycache__"
            )
            for f in sorted(filenames):
                if not f.endswith(".py"):
                    continue
                norm = os.path.normpath(os.path.join(dirpath, f))
                if norm in seen:
                    continue
                seen.add(norm)
                yield norm


def _analyze_file(source, path, rules):
    """(findings, summary, suppression-map) for one file.

    *summary* is None on parse errors (the PARSE-ERROR finding carries
    the news; program rules skip the file).  The file is parsed and
    tokenized exactly once, shared between the per-file rules and the
    callgraph summary.
    """
    from client_tpu.analysis import callgraph

    lines = source.splitlines()
    by_line, bare = parse_suppressions(lines)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return scan_source(source, path, rules), None, by_line
    findings = scan_source(
        source, path, rules, tree=tree, parsed_suppressions=(by_line, bare)
    )
    summary = callgraph.summarize_module(tree, path)
    return findings, summary, by_line


def scan_paths(paths, rules=None, exclude_parts=("analysis_fixtures",),
               cache=None, program_rules=None):
    """Scan files and the program they form.

    ``rules``/``program_rules``: None = all registered; pass a dict to
    filter (an empty dict disables that family).  ``cache`` is an
    optional :class:`client_tpu.analysis.cache.AnalysisCache` reused
    across runs — only consulted for full-default-rule scans (a filtered
    scan must not poison or be poisoned by cached full results).
    """
    from client_tpu.analysis import callgraph

    use_cache = cache is not None and rules is None
    findings = []
    summaries = []
    suppress_by_path = {}
    snippet_lines = {}  # program-finding snippets come from the source
    for path in iter_python_files(paths, exclude_parts):
        entry = cache.get(path) if use_cache else None
        if entry is not None:
            file_findings = [Finding(**f) for f in entry["findings"]]
            summary = (
                callgraph.ModuleSummary.from_dict(entry["summary"])
                if entry["summary"] is not None
                else None
            )
            by_line = {
                int(k): set(v) for k, v in entry["suppress"].items()
            }
        else:
            # stat BEFORE reading: a save landing mid-analysis must leave
            # the entry stale (re-scan next run), never fresh-looking
            stat_key = cache.stat_key(path) if use_cache else None
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    source = fh.read()
            except OSError as e:
                findings.append(
                    Finding("READ-ERROR", path, 1, 0, f"unreadable: {e}", "")
                )
                continue
            file_findings, summary, by_line = _analyze_file(
                source, path, rules
            )
            # keep THIS run's lines for program-finding snippets: a save
            # landing mid-run must not produce a snippet (the baseline's
            # drift-stable key) from content nobody analyzed
            snippet_lines[path] = source.splitlines()
            if use_cache:
                cache.put(path, {
                    "findings": [f.to_dict() for f in file_findings],
                    "summary": (
                        summary.to_dict() if summary is not None else None
                    ),
                    "suppress": {
                        str(k): sorted(v) for k, v in by_line.items()
                    },
                }, stat_key)
        findings.extend(file_findings)
        if summary is not None:
            summaries.append(summary)
            suppress_by_path[path] = by_line

    active_program = (
        PROGRAM_REGISTRY if program_rules is None else program_rules
    )
    if active_program and summaries:
        program = callgraph.build_program(summaries)
        reported = set()
        program_findings = []
        for rule in active_program.values():
            for f in rule.check_program(program):
                by_line = suppress_by_path.get(f.path, {})
                if _suppressed(f, by_line):
                    continue
                # message is part of the key: two DISTINCT cycles can
                # anchor on the same witness line (a call made under two
                # held locks); only true duplicates may collapse
                key = (f.rule, f.path, f.line, f.message)
                if key in reported:
                    continue
                reported.add(key)
                if f.path not in snippet_lines:
                    # cache-hit file: its source was not read this run
                    try:
                        with open(f.path, "r", encoding="utf-8") as fh:
                            snippet_lines[f.path] = fh.read().splitlines()
                    except OSError:
                        snippet_lines[f.path] = []
                lines = snippet_lines[f.path]
                snippet = (
                    lines[f.line - 1].strip()
                    if 1 <= f.line <= len(lines)
                    else ""
                )
                program_findings.append(
                    dataclasses.replace(f, snippet=snippet)
                )
        findings.extend(program_findings)

    if use_cache:
        cache.save()
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


@register
class BareSuppressRule(Rule):
    """BARE-SUPPRESS — suppression comments without a ``-- reason``.

    Every waiver is a decision someone later has to re-audit; a bare
    ``# tpulint: disable=RULE`` records the decision without the
    reasoning, so the next reader cannot tell a load-bearing exemption
    from a drive-by silence.  The reason rides in the comment itself:
    ``# tpulint: disable=RULE -- why this is safe``.  BARE-SUPPRESS
    findings cannot themselves be suppressed.
    """

    id = "BARE-SUPPRESS"
    rationale = (
        "a suppression without a reason cannot be audited — write "
        "`# tpulint: disable=RULE -- why`"
    )

    def check(self, tree, lines, path):
        _by_line, bare = parse_suppressions(lines)
        return self.check_parsed(bare, lines, path)

    def check_parsed(self, bare, lines, path):
        """The driver hands over its already-parsed suppressions so the
        file is tokenized once, not once per consumer."""
        findings = []
        for line, ids in bare:
            what = (
                "all rules" if _ALL in ids else ", ".join(sorted(ids))
            )
            snippet = lines[line - 1].strip() if line <= len(lines) else ""
            findings.append(Finding(
                self.id, path, line, 0,
                f"suppression of {what} carries no reason — append "
                "`-- <why this is safe>`", snippet,
            ))
        return findings
