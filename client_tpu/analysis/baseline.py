"""Baseline ratchet for tpu-lint.

Grandfathered findings live in a committed JSON file keyed by
``(path, rule, stripped-source-line)`` — stable across pure line-number
drift.  The gate starts green on the day the analyzer lands and only
ratchets DOWN: a finding matching a baseline entry is filtered; a new
finding (or one more occurrence of a baselined line than the baseline
carries) fails the run.  ``--write-baseline`` regenerates the file from
the current tree after a deliberate cleanup.
"""

import collections
import json
import os

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def load(path):
    """Return the baseline as a Counter of finding keys; {} if absent."""
    if not path or not os.path.exists(path):
        return collections.Counter()
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    counter = collections.Counter()
    for entry in data.get("findings", []):
        key = (entry["path"], entry["rule"], entry["snippet"])
        counter[key] += int(entry.get("count", 1))
    return counter


def save(path, findings):
    """Write the given findings as the new baseline (sorted, counted)."""
    counter = collections.Counter(f.key() for f in findings)
    entries = [
        {"path": p, "rule": r, "snippet": s, "count": n}
        for (p, r, s), n in sorted(counter.items())
    ]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(
            {
                "comment": (
                    "tpu-lint grandfathered findings; regenerate with "
                    "python -m client_tpu.analysis --write-baseline"
                ),
                "findings": entries,
            },
            fh, indent=2, sort_keys=True,
        )
        fh.write("\n")


def filter_findings(findings, baseline_counter):
    """Split findings into (new, grandfathered) against the baseline.

    Occurrences beyond the baselined count for a key are NEW — the
    ratchet lets old debt stand but never grow.
    """
    remaining = collections.Counter(baseline_counter)
    new, old = [], []
    for f in findings:
        if remaining[f.key()] > 0:
            remaining[f.key()] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
