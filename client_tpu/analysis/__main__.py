"""CLI: ``python -m client_tpu.analysis [paths...]``.

Exit codes: 0 clean (after baseline filtering), 1 findings, 2 analyzer
usage/internal error.  ``make lint`` runs this over ``client_tpu tests``.
"""

import argparse
import os
import sys

from client_tpu.analysis import REGISTRY, scan_paths
from client_tpu.analysis import baseline as baseline_mod
from client_tpu.analysis import report


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m client_tpu.analysis",
        description=(
            "tpu-lint: concurrency & array-semantics rules grown from "
            "this repo's shipped bugs"
        ),
    )
    parser.add_argument(
        "paths", nargs="*", default=["client_tpu", "tests"],
        help="files or directories to scan (default: client_tpu tests)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--baseline", default=baseline_mod.DEFAULT_BASELINE,
        help="baseline file of grandfathered findings",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring the baseline",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="regenerate the baseline from the current findings and exit 0",
    )
    parser.add_argument(
        "--rules", default="",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        print(report.render_rules(REGISTRY))
        return 0

    rules = REGISTRY
    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        unknown = wanted - set(REGISTRY)
        if unknown:
            print(
                f"tpu-lint: unknown rule(s): {', '.join(sorted(unknown))}",
                file=sys.stderr,
            )
            return 2
        rules = {k: v for k, v in REGISTRY.items() if k in wanted}

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        # a typo'd path must not turn the gate into a silent green no-op
        print(
            f"tpu-lint: no such path(s): {', '.join(missing)}",
            file=sys.stderr,
        )
        return 2

    findings = scan_paths(args.paths, rules=rules)

    if args.write_baseline:
        if args.rules or args.paths != parser.get_default("paths"):
            # a filtered scan would overwrite the whole file and silently
            # drop every other rule's/path's grandfathered entries
            print(
                "tpu-lint: --write-baseline requires a full default scan "
                "(no --rules, default paths)",
                file=sys.stderr,
            )
            return 2
        baseline_mod.save(args.baseline, findings)
        print(
            f"tpu-lint: wrote {len(findings)} finding(s) to "
            f"{args.baseline}"
        )
        return 0

    baseline = (
        {} if args.no_baseline else baseline_mod.load(args.baseline)
    )
    new, old = baseline_mod.filter_findings(findings, baseline)

    if args.json:
        print(report.render_json(new, old, rules))
    else:
        print(report.render_text(new, old, rules))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
