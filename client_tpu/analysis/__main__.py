"""CLI: ``python -m client_tpu.analysis [paths...]``.

Exit codes: 0 clean (after baseline filtering), 1 findings, 2 analyzer
usage/internal error.  ``make lint`` runs this over ``client_tpu tests``;
``make lint-strict`` adds ``examples``.
"""

import argparse
import os
import subprocess
import sys

from client_tpu.analysis import (
    PROGRAM_REGISTRY,
    REGISTRY,
    all_rules,
    scan_paths,
)
from client_tpu.analysis import baseline as baseline_mod
from client_tpu.analysis import cache as cache_mod
from client_tpu.analysis import report


def _changed_files():
    """Files changed vs the merge base with origin/main (falling back to
    a local main, then to the index alone), plus untracked files —
    normalized paths, or None when git itself is unusable (the caller
    errors loudly: a silently-empty changed set would green-light
    anything)."""
    def git(*args):
        try:
            proc = subprocess.run(
                ["git", *args], capture_output=True, text=True,
                timeout=30,
            )
        except (subprocess.TimeoutExpired, OSError):
            # a hung git (stale index lock, dead network fs) must reach
            # the caller's loud exit-2 path, not die in a traceback
            return None
        if proc.returncode != 0:
            return None
        return proc.stdout

    toplevel = git("rev-parse", "--show-toplevel")
    if not toplevel:
        return None
    toplevel = toplevel.strip()
    base = None
    for ref in ("origin/main", "main"):
        out = git("merge-base", "HEAD", ref)
        if out:
            base = out.strip()
            break
    diff = git("diff", "--name-only", base) if base else git(
        "diff", "--name-only", "HEAD"
    )
    if diff is None:
        return None
    untracked = git("ls-files", "--others", "--exclude-standard")
    names = diff.splitlines() + (
        untracked.splitlines() if untracked else []
    )
    # git names are repo-root-relative; finding paths are CLI-relative
    # (or absolute) — compare on one realpath basis so an absolute scan
    # root or a subdirectory cwd cannot silently empty the changed set
    return {
        os.path.realpath(os.path.join(toplevel, n))
        for n in names if n.strip()
    }


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m client_tpu.analysis",
        description=(
            "tpu-lint: concurrency & array-semantics rules grown from "
            "this repo's shipped bugs (per-file AST rules + whole-program "
            "call-graph/lock-order analysis)"
        ),
    )
    parser.add_argument(
        "paths", nargs="*", default=["client_tpu", "tests"],
        help="files or directories to scan (default: client_tpu tests)",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help=(
            "report format (json is the machine-readable CI surface; "
            "sarif is SARIF 2.1.0 for CI annotators and editors — "
            "`make lint-sarif` writes build/lint.sarif)"
        ),
    )
    parser.add_argument(
        "--json", action="store_true",
        help="alias for --format json",
    )
    parser.add_argument(
        "--changed-only", action="store_true",
        help=(
            "report per-file findings only for files changed vs "
            "`git merge-base HEAD origin/main` (plus untracked files); "
            "the whole-program passes still run over the full tree — "
            "warm from cache — so cross-file findings never go dark. "
            "The fast pre-commit path."
        ),
    )
    parser.add_argument(
        "--baseline", default=baseline_mod.DEFAULT_BASELINE,
        help="baseline file of grandfathered findings",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, ignoring the baseline",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="regenerate the baseline from the current findings and exit 0",
    )
    parser.add_argument(
        "--rules", default="",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    parser.add_argument(
        "--explain", metavar="RULE", default="",
        help="print one rule's full rationale and exit",
    )
    parser.add_argument(
        "--no-cache", action="store_true",
        help="ignore and do not write the incremental analysis cache",
    )
    parser.add_argument(
        "--cache-file", default=cache_mod.DEFAULT_CACHE,
        help="incremental cache location (default: alongside the analyzer)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        print(report.render_rules(all_rules()))
        return 0

    if args.explain:
        text = report.render_explain(all_rules(), args.explain)
        if text is None:
            print(
                f"tpu-lint: unknown rule {args.explain!r} "
                "(see --list-rules)",
                file=sys.stderr,
            )
            return 2
        print(text)
        return 0

    rules = None  # None = full default rule set (cache-eligible)
    program_rules = None
    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        known = all_rules()
        unknown = wanted - set(known)
        if unknown:
            print(
                f"tpu-lint: unknown rule(s): {', '.join(sorted(unknown))}",
                file=sys.stderr,
            )
            return 2
        rules = {k: v for k, v in REGISTRY.items() if k in wanted}
        program_rules = {
            k: v for k, v in PROGRAM_REGISTRY.items() if k in wanted
        }

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        # a typo'd path must not turn the gate into a silent green no-op
        print(
            f"tpu-lint: no such path(s): {', '.join(missing)}",
            file=sys.stderr,
        )
        return 2

    analysis_cache = (
        None if args.no_cache else cache_mod.AnalysisCache(args.cache_file)
    )
    findings = scan_paths(
        args.paths, rules=rules, cache=analysis_cache,
        program_rules=program_rules,
    )

    if args.changed_only:
        changed = _changed_files()
        if changed is None:
            print(
                "tpu-lint: --changed-only needs a working git checkout "
                "(git diff failed)",
                file=sys.stderr,
            )
            return 2
        # per-file findings (the waiver audit included) narrow to the
        # changed set; whole-program findings keep their full-tree
        # scope — a cross-file hazard introduced by a changed file can
        # anchor in an unchanged one
        findings = [
            f for f in findings
            if f.rule not in REGISTRY
            or os.path.realpath(f.path) in changed
        ]

    if args.write_baseline:
        if (
            args.rules
            or args.changed_only
            or args.paths != parser.get_default("paths")
        ):
            # a filtered scan would overwrite the whole file and silently
            # drop every other rule's/path's grandfathered entries
            print(
                "tpu-lint: --write-baseline requires a full default scan "
                "(no --rules, no --changed-only, default paths)",
                file=sys.stderr,
            )
            return 2
        baseline_mod.save(args.baseline, findings)
        print(
            f"tpu-lint: wrote {len(findings)} finding(s) to "
            f"{args.baseline}"
        )
        return 0

    baseline = (
        {} if args.no_baseline else baseline_mod.load(args.baseline)
    )
    new, old = baseline_mod.filter_findings(findings, baseline)

    if args.json or args.format == "json":
        print(report.render_json(new, old, all_rules()))
    elif args.format == "sarif":
        print(report.render_sarif(new, old, all_rules()))
    else:
        print(report.render_text(new, old, all_rules()))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
