"""tpu-lint rule catalog.

Every rule encodes an invariant this repo has actually shipped (or nearly
shipped) a bug against — the rationale strings name the incident.  All
rules are lexical/AST heuristics: they prefer missing an exotic variant
over drowning the gate in false positives, and every finding can be
waived in place with ``# tpulint: disable=RULE`` plus a rationale.
"""

import ast
import re

from client_tpu.analysis.core import Rule, register

# receivers that look like a mutex/condvar (last dotted segment)
_LOCKISH_RE = re.compile(r"(?i)(lock|mutex|cv|cond)")
# receivers that look specifically like a condition variable
_CVLIKE_RE = re.compile(r"(?i)(^|_)(cv|cond|condition)s?$")
# numpy array-producing module functions (np./numpy. namespaces)
_NP_ARRAY_FNS = {
    "asarray", "array", "zeros", "ones", "empty", "full", "arange",
    "linspace", "concatenate", "stack", "frombuffer", "where", "reshape",
    "copy", "asanyarray", "atleast_1d", "squeeze",
}
# device-dispatch callees beyond jit-bound names (last dotted segment)
_DISPATCH_HINTS = {"prefill", "decode_step", "block_until_ready"}
_DISPATCH_FULL = {
    "jax.device_put", "jax.device_get", "jax.block_until_ready",
}
# constructors whose assignment targets become jit-compiled callables
_JIT_CTORS = ("jax.jit", "jit", "jax.pmap", "pmap")


def _jit_bound_names(tree):
    """Names (bare or ``self.x``) assigned from jax.jit/pmap anywhere in
    *tree*.  Shared by the lexical LOCK-DISPATCH rule and the callgraph
    summaries so both rule families agree on what a dispatch is."""
    bound = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            text = _expr_text(node.value.func) or ""
            if text in _JIT_CTORS:
                for t in node.targets:
                    tt = _expr_text(t)
                    if tt:
                        bound.add(tt)
    return bound


# blocking callees never allowed in an async def body
_ASYNC_BLOCKING_FULL = {
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_output",
    "subprocess.check_call",
    "socket.create_connection",
}
_ASYNC_BLOCKING_PREFIXES = ("requests.",)
# queue.Queue constructors whose get/put block without a timeout
_QUEUE_CTORS = {
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "Queue", "SimpleQueue",
}
# names that hold a point-in-time budget (deadline semantics)
_DEADLINE_NAME_RE = re.compile(r"(?i)(deadline|expires?|expiry|_until$|^until$)")
# Prometheus label position: an f-string constant part ending with
# `label="` right before an interpolated value
_LABEL_OPEN_RE = re.compile(r'[A-Za-z_][A-Za-z0-9_]*="$')
# sanctioned escape helpers for label values (serve/metrics.escape_label)
_LABEL_ESCAPERS = {"escape_label", "_escape_label"}
# in-place collection mutators (list/dict/set/deque) that race readers just
# like an assignment does — the discovery-membership shape (SHARED-MUT).
# Deliberately excludes names that are atomic/thread-safe on their common
# receivers (queue put/get, Event set/clear) to keep the gate quiet.
_COLLECTION_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "clear", "discard", "popitem", "setdefault",
}


def _expr_text(node):
    """Dotted text for Name/Attribute chains ('self._cv'); None otherwise."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_text(node.value)
        if base is None:
            return None
        return base + "." + node.attr
    return None


def _last_segment(text):
    return text.rsplit(".", 1)[-1] if text else ""


def _walk_no_functions(node):
    """Yield descendants without crossing into nested function bodies."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        stack.extend(ast.iter_child_nodes(child))


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _is_lockish_with(node):
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return False
    for item in node.items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Call):
            ctx = ctx.func
        text = _expr_text(ctx)
        if text and _LOCKISH_RE.search(_last_segment(text)):
            return True
    return False


@register
class NpyTruthRule(Rule):
    """NPY-TRUTH — numpy values in truthiness / membership positions.

    ``bool(array)`` raises ("truth value of an array is ambiguous") and
    list membership / ``remove`` compare elementwise — the exact crash
    fixed in commit a2654c4 (``cancel()`` did ``handle in self._pending``
    over entries holding numpy prompts).  Tracks names assigned from
    np/jnp array producers in the same function, plus list/tuple literals
    containing them (containers compare elementwise too).
    """

    id = "NPY-TRUTH"
    rationale = (
        "numpy truthiness raises and membership compares elementwise "
        "(the a2654c4 cancel() crash)"
    )

    def _is_numpy_expr(self, node):
        if not isinstance(node, ast.Call):
            return False
        func = node.func
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name):
                if base.id == "jnp":
                    return True
                if base.id in ("np", "numpy"):
                    return func.attr in _NP_ARRAY_FNS
            # method chain on a numpy expression: np.asarray(x).reshape(...)
            if isinstance(base, ast.Call) and self._is_numpy_expr(base):
                return True
        return False

    def _collect_taint(self, fn):
        # two passes: container taint depends on the full array-name set
        # (tree walk order is not statement order)
        assigns = [
            node
            for node in _walk_no_functions(fn)
            if isinstance(node, ast.Assign)
        ]
        arrays, containers = set(), set()
        for node in assigns:
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if targets and self._is_numpy_expr(node.value):
                arrays.update(targets)
        for node in assigns:
            targets = [
                t.id for t in node.targets if isinstance(t, ast.Name)
            ]
            if (
                targets
                and isinstance(node.value, (ast.List, ast.Tuple))
                and any(
                    isinstance(el, ast.Name) and el.id in arrays
                    for el in node.value.elts
                )
            ):
                containers.update(targets)
        return arrays, containers

    def _tainted(self, node, arrays, containers):
        if isinstance(node, ast.Name):
            return node.id in arrays or node.id in containers
        return self._is_numpy_expr(node)

    def _array_tainted(self, node, arrays):
        if isinstance(node, ast.Name):
            return node.id in arrays
        return self._is_numpy_expr(node)

    def check(self, tree, lines, path):
        findings = []
        for fn in list(_functions(tree)) + [tree]:
            arrays, containers = self._collect_taint(fn)
            if not arrays and not containers:
                continue
            for node in _walk_no_functions(fn):
                findings.extend(
                    self._check_node(node, arrays, containers, path, lines)
                )
        for cls in ast.walk(tree):
            if isinstance(cls, ast.ClassDef):
                findings.extend(self._check_class_attrs(cls, lines, path))
        return findings

    def _check_class_attrs(self, cls, lines, path):
        """Cross-method taint: a self-attribute collection that any method
        appends numpy-bearing entries into makes EVERY membership/remove
        over it elementwise — the exact a2654c4 cancel() crash, where the
        numpy-bearing handle arrived as a parameter and only submit()
        showed the taint."""
        methods = [
            n for n in ast.walk(cls)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        npy_attrs = set()
        for fn in methods:
            arrays, containers = self._collect_taint(fn)
            if not arrays and not containers:
                continue
            for node in _walk_no_functions(fn):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("append", "insert", "add")
                ):
                    continue
                recv = _expr_text(node.func.value)
                if (
                    recv
                    and recv.startswith("self.")
                    and any(
                        isinstance(a, ast.Name)
                        and (a.id in arrays or a.id in containers)
                        for a in node.args
                    )
                ):
                    npy_attrs.add(recv)
        if not npy_attrs:
            return []
        out = []
        for fn in methods:
            for node in _walk_no_functions(fn):
                if isinstance(node, ast.Compare) and any(
                    isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
                ):
                    sides = [node.left] + list(node.comparators)
                    hit = next(
                        (
                            _expr_text(s)
                            for s in sides
                            if _expr_text(s) in npy_attrs
                        ),
                        None,
                    )
                    if hit:
                        out.append(self.finding(
                            path, lines, node,
                            f"membership over {hit}, which holds "
                            "numpy-bearing entries: compares elementwise "
                            "and raises (scan by identity instead)",
                        ))
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("remove", "index", "count")
                    and _expr_text(node.func.value) in npy_attrs
                ):
                    out.append(self.finding(
                        path, lines, node,
                        f".{node.func.attr}() on "
                        f"{_expr_text(node.func.value)}, which holds "
                        "numpy-bearing entries: compares elementwise and "
                        "raises (scan by identity instead)",
                    ))
        return out

    def _check_node(self, node, arrays, containers, path, lines):
        out = []
        # truthiness: if/while/ternary/assert/not/and/or over a raw array
        tests = []
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            tests.append(node.test)
        elif isinstance(node, ast.Assert):
            tests.append(node.test)
        for test in tests:
            operands = (
                test.values if isinstance(test, ast.BoolOp) else [test]
            )
            for op in operands:
                if isinstance(op, ast.UnaryOp) and isinstance(
                    op.op, ast.Not
                ):
                    op = op.operand
                if self._array_tainted(op, arrays):
                    out.append(self.finding(
                        path, lines, node,
                        "numpy value used for truthiness (ambiguous "
                        "bool raises at runtime)",
                    ))
        if isinstance(node, ast.Call):
            func = node.func
            # bool(arr)
            if (
                isinstance(func, ast.Name) and func.id == "bool"
                and node.args
                and self._array_tainted(node.args[0], arrays)
            ):
                out.append(self.finding(
                    path, lines, node,
                    "bool() over a numpy value raises (ambiguous truth)",
                ))
            # pending.remove(arr) / .index / .count compare elementwise
            if (
                isinstance(func, ast.Attribute)
                and func.attr in ("remove", "index", "count")
                and node.args
                and self._tainted(node.args[0], arrays, containers)
            ):
                out.append(self.finding(
                    path, lines, node,
                    f".{func.attr}() with a numpy-bearing argument "
                    "compares elementwise and raises on match ambiguity",
                ))
        if isinstance(node, ast.Compare) and any(
            isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
        ):
            sides = [node.left] + list(node.comparators)
            if any(self._tainted(s, arrays, containers) for s in sides):
                out.append(self.finding(
                    path, lines, node,
                    "membership test over numpy-bearing values compares "
                    "elementwise (scan by identity instead)",
                ))
        return out


@register
class AsyncBlockRule(Rule):
    """ASYNC-BLOCK — blocking calls inside ``async def`` bodies.

    One blocking call inside the aio clients or the serving event loop
    stalls every coroutine sharing that loop.  Flags time.sleep /
    requests.* / subprocess.* and timeout-less queue.Queue get/put on
    queues constructed in the same function or bound to ``self`` in the
    same class.
    """

    id = "ASYNC-BLOCK"
    rationale = (
        "a blocking call in an async body stalls the whole event loop "
        "(aio clients, serve/)"
    )

    @staticmethod
    def _queue_call_blocks(call, bounded):
        """True when a queue .get/.put call can block indefinitely.

        Signatures: ``get(block=True, timeout=None)`` and
        ``put(item, block=True, timeout=None)`` — the positional slots
        differ by one, ``block=False`` never blocks, and ``put`` on an
        unbounded queue (no maxsize at construction) never blocks.
        """
        if call.func.attr == "put" and not bounded:
            return False
        kwargs = {kw.arg: kw.value for kw in call.keywords}
        first = 0 if call.func.attr == "get" else 1  # skip put's item
        positional = call.args[first:]
        block = kwargs.get("block", positional[0] if positional else None)
        if isinstance(block, ast.Constant) and block.value is False:
            return False  # non-blocking variant
        has_timeout = "timeout" in kwargs or len(positional) >= 2
        return not has_timeout

    @staticmethod
    def _ctor_is_bounded(ctor):
        """queue.Queue(maxsize>0) blocks on put; bare/0 never does."""
        sized = list(ctor.args) + [
            kw.value for kw in ctor.keywords if kw.arg == "maxsize"
        ]
        if not sized:
            return False
        arg = sized[0]
        return not (isinstance(arg, ast.Constant) and arg.value == 0)

    def _queue_attrs(self, cls):
        attrs = {}
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if _expr_text(node.value.func) in _QUEUE_CTORS:
                    for t in node.targets:
                        text = _expr_text(t)
                        if text and text.startswith("self."):
                            attrs[text] = self._ctor_is_bounded(node.value)
        return attrs

    def check(self, tree, lines, path):
        findings = []
        class_queue_attrs = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                qattrs = self._queue_attrs(node)
                for sub in ast.walk(node):
                    if isinstance(sub, ast.AsyncFunctionDef):
                        class_queue_attrs[id(sub)] = qattrs
        for fn in _functions(tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            qnames = {
                t.id: self._ctor_is_bounded(node.value)
                for node in _walk_no_functions(fn)
                if isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _expr_text(node.value.func) in _QUEUE_CTORS
                for t in node.targets
                if isinstance(t, ast.Name)
            }
            qattrs = class_queue_attrs.get(id(fn), {})
            for node in _walk_no_functions(fn):
                if not isinstance(node, ast.Call):
                    continue
                text = _expr_text(node.func) or ""
                if text in _ASYNC_BLOCKING_FULL or text.startswith(
                    _ASYNC_BLOCKING_PREFIXES
                ):
                    findings.append(self.finding(
                        path, lines, node,
                        f"blocking call {text}() inside async def "
                        f"{fn.name} stalls the event loop",
                    ))
                    continue
                if isinstance(node.func, ast.Attribute) and node.func.attr in (
                    "get", "put"
                ):
                    recv = _expr_text(node.func.value)
                    is_queue = recv in qnames or recv in qattrs
                    bounded = qnames.get(recv, qattrs.get(recv, False))
                    if is_queue and self._queue_call_blocks(node, bounded):
                        findings.append(self.finding(
                            path, lines, node,
                            f"sync {recv}.{node.func.attr}() without "
                            f"timeout inside async def {fn.name} blocks "
                            "the event loop",
                        ))
        return findings


@register
class LockDispatchRule(Rule):
    """LOCK-DISPATCH — device dispatch while holding a scheduler lock.

    jax.jit compiles per novel input signature; a dispatch under
    ``with self._cv:`` holds the lock for a full XLA compile (seconds)
    and head-of-line-blocks every other thread (the pre-fix
    ``_admit_locked`` prefill in serve/models/continuous.py).  Lock-held
    regions are lexical ``with *lock/cv/cond:`` bodies plus whole methods
    named ``*_locked`` (this codebase's caller-holds-the-lock
    convention).  Dispatch callees are names bound from ``jax.jit(...)``
    anywhere in the module, jax.device_put/get/block_until_ready, and
    the prefill/decode_step hint names.
    """

    id = "LOCK-DISPATCH"
    rationale = (
        "device dispatch under a lock head-of-line-blocks every waiter "
        "for a full XLA compile (continuous.py _admit_locked)"
    )

    def _is_dispatch(self, call, jit_bound):
        text = _expr_text(call.func)
        if not text:
            return None
        if text in jit_bound:
            return f"jit-compiled callable {text}()"
        if text in _DISPATCH_FULL:
            return f"{text}()"
        if _last_segment(text) in _DISPATCH_HINTS:
            return f"device-dispatch {text}()"
        return None

    def check(self, tree, lines, path):
        jit_bound = _jit_bound_names(tree)
        findings = []
        regions = []
        for node in ast.walk(tree):
            if _is_lockish_with(node):
                regions.append((node, "with-lock block"))
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ) and node.name.endswith("_locked"):
                regions.append((node, f"lock-held method {node.name}"))
        seen = set()
        for region, where in regions:
            for node in ast.walk(region):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                what = self._is_dispatch(node, jit_bound)
                if what:
                    seen.add(id(node))
                    findings.append(self.finding(
                        path, lines, node,
                        f"{what} dispatched inside {where}: holds the "
                        "lock across a potential XLA compile — move the "
                        "dispatch outside the critical section",
                    ))
        return findings


@register
class QueueSentinelRule(Rule):
    """QUEUE-SENTINEL — deactivating a streaming slot without closing
    its queue.

    A per-request token queue's reader blocks on ``get()`` until the
    close sentinel arrives; any path that flips ``<slot>.active = False``
    without a ``<slot>.queue.put(...)`` in the same branch strands that
    reader forever (the pre-fix active-slot branch of ``cancel()`` in
    serve/models/continuous.py).  Applies to receivers that have a
    ``.queue`` attribute somewhere in the same module.
    """

    id = "QUEUE-SENTINEL"
    rationale = (
        "slot deactivated without enqueueing the close sentinel strands "
        "the stream reader (continuous.py cancel() on an active slot)"
    )

    def check(self, tree, lines, path):
        queue_receivers = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and node.attr == "queue":
                text = _expr_text(node.value)
                if text:
                    queue_receivers.add(text)
        if not queue_receivers:
            return []

        # constructor bodies initialize .active = False; that is not a
        # deactivation and has no reader to strand yet
        in_init = set()
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.FunctionDef)
                and node.name == "__init__"
            ):
                for sub in ast.walk(node):
                    in_init.add(id(sub))

        # map each statement to its containing body list (its branch)
        blocks = {}
        for node in ast.walk(tree):
            for field in ("body", "orelse", "finalbody"):
                body = getattr(node, field, None)
                if isinstance(body, list):
                    for stmt in body:
                        blocks[id(stmt)] = body

        def block_has_close(body, recv):
            put_text = recv + ".queue.put"
            for stmt in body:
                for sub in ast.walk(stmt):
                    if (
                        isinstance(sub, ast.Call)
                        and _expr_text(sub.func) == put_text
                    ):
                        return True
            return False

        findings = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or id(node) in in_init:
                continue
            for target in node.targets:
                if not (
                    isinstance(target, ast.Attribute)
                    and target.attr == "active"
                ):
                    continue
                if not (
                    isinstance(node.value, ast.Constant)
                    and node.value.value is False
                ):
                    continue
                recv = _expr_text(target.value)
                if recv not in queue_receivers:
                    continue
                body = blocks.get(id(node))
                if body is not None and not block_has_close(body, recv):
                    findings.append(self.finding(
                        path, lines, node,
                        f"{recv}.active = False without "
                        f"{recv}.queue.put(<close sentinel>) in the same "
                        "branch: a queue reader will hang on get()",
                    ))
        return findings


@register
class CvWaitLoopRule(Rule):
    """CV-WAIT-LOOP — ``Condition.wait()`` outside a predicate loop.

    Condition variables wake spuriously and predicates can be consumed
    by other waiters: every cv-like ``.wait()`` must sit inside a loop
    that re-checks its predicate (or use ``wait_for``).  Receivers are
    matched by name (``*_cv``, ``*_cond``, ``condition``).
    """

    id = "CV-WAIT-LOOP"
    rationale = (
        "cv.wait() without an enclosing predicate loop misses wakeups "
        "and acts on stale state"
    )

    def check(self, tree, lines, path):
        findings = []
        for fn in list(_functions(tree)) + [tree]:
            loops = set()
            for node in _walk_no_functions(fn):
                if isinstance(node, (ast.While, ast.For)):
                    loops.add(id(node))
                    for sub in ast.walk(node):
                        loops.add(id(sub))
            for node in _walk_no_functions(fn):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "wait"
                ):
                    continue
                recv = _expr_text(node.func.value)
                if not recv or not _CVLIKE_RE.search(_last_segment(recv)):
                    continue
                if id(node) not in loops:
                    findings.append(self.finding(
                        path, lines, node,
                        f"{recv}.wait() outside a predicate re-check "
                        "loop: wrap in `while <predicate>:` or use "
                        "wait_for()",
                    ))
        return findings


@register
class TimeWallRule(Rule):
    """TIME-WALL — deadlines computed from the wall clock.

    ``time.time()`` jumps under NTP slew/step and DST-adjacent clock
    management; a deadline derived from it can expire instantly (every
    in-flight wait aborts) or never (a drain that hangs).  Every
    point-in-time budget must come from ``time.monotonic()`` — the
    invariant the resilience layer's Deadline/backoff code is built on.
    Flags (a) assignments of ``time.time()``-derived values to
    deadline-named targets and (b) comparisons between ``time.time()``
    and a deadline-named value.  Wall-clock *timestamps* (metrics, log
    fields) are untouched: the rule keys on deadline naming.
    """

    id = "TIME-WALL"
    rationale = (
        "a wall-clock deadline jumps with NTP adjustment: expires "
        "instantly or never (use time.monotonic())"
    )

    @staticmethod
    def _has_wall_clock_call(node):
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and _expr_text(sub.func) == "time.time"
            ):
                return True
        return False

    @staticmethod
    def _is_deadline_name(node):
        text = _expr_text(node)
        return bool(text and _DEADLINE_NAME_RE.search(_last_segment(text)))

    def check(self, tree, lines, path):
        findings = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                if node.value is None:  # bare annotation: no computation
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                if any(self._is_deadline_name(t) for t in targets) and (
                    self._has_wall_clock_call(node.value)
                ):
                    findings.append(self.finding(
                        path, lines, node,
                        "deadline computed from time.time(): wall-clock "
                        "jumps (NTP) break the budget — use "
                        "time.monotonic()",
                    ))
            elif isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                has_wall = any(self._has_wall_clock_call(s) for s in sides)
                has_deadline = any(self._is_deadline_name(s) for s in sides)
                if has_wall and has_deadline:
                    findings.append(self.finding(
                        path, lines, node,
                        "deadline compared against time.time(): wall-clock "
                        "jumps (NTP) break the budget — use "
                        "time.monotonic()",
                    ))
        return findings


@register
class MetricLabelRule(Rule):
    """METRIC-LABEL — unescaped interpolation into Prometheus label values.

    The text exposition format reserves ``\\``, ``"`` and newline inside
    quoted label values; an f-string that drops a model/version name into
    ``{model="..."}`` unescaped lets one hostile (or merely creative) model
    name corrupt the whole /metrics payload — the serve/metrics.py bug this
    PR fixed.  Flags any f-string FormattedValue whose preceding constant
    part ends in ``label="`` unless the value is wrapped in the sanctioned
    escape helper (``escape_label``).
    """

    id = "METRIC-LABEL"
    rationale = (
        "a quote/backslash/newline interpolated into a Prometheus label "
        "corrupts the exposition format (wrap the value in escape_label())"
    )

    def check(self, tree, lines, path):
        findings = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.JoinedStr):
                continue
            prev_const = ""
            for part in node.values:
                if isinstance(part, ast.Constant) and isinstance(
                    part.value, str
                ):
                    prev_const = part.value
                    continue
                if isinstance(part, ast.FormattedValue):
                    if _LABEL_OPEN_RE.search(prev_const):
                        escaper = ""
                        if isinstance(part.value, ast.Call):
                            escaper = _last_segment(
                                _expr_text(part.value.func) or ""
                            )
                        if escaper not in _LABEL_ESCAPERS:
                            label = _LABEL_OPEN_RE.search(prev_const).group()
                            what = _expr_text(part.value) or "<expression>"
                            findings.append(self.finding(
                                path, lines, part,
                                f"f-string interpolates {what} into the "
                                f"Prometheus label position {label}...\" "
                                "without escape_label(): a quote/backslash/"
                                "newline in the value corrupts the "
                                "exposition format",
                            ))
                    prev_const = ""
        return findings


@register
class RespParamOverwriteRule(Rule):
    """RESP-PARAM-OVERWRITE — dict-literal assignment stamps a marker over
    shared response parameters.

    ``response["parameters"] = {"some_flag": True}`` REPLACES whatever
    response-level parameters the model or an earlier render step set —
    the silent-vanish bug the decoupled stream's ``triton_final_response``
    stamp shipped (ADVICE round 5: model-set params disappeared once
    grpc_server started forwarding response parameters).  The sanctioned
    shape merges instead::

        response.setdefault("parameters", {})["some_flag"] = True

    Heuristic: flags assignments of a dict LITERAL carrying at least one
    boolean-constant value (the marker-stamp shape) to a ``["parameters"]``
    subscript, unless the subscripted object is a dict literal freshly
    built in the same function (constructing a new response is not an
    overwrite — there is nothing to lose yet).
    """

    id = "RESP-PARAM-OVERWRITE"
    rationale = (
        "assigning a marker dict to [\"parameters\"] replaces model-set "
        "response parameters (merge via setdefault instead)"
    )

    @staticmethod
    def _fresh_dict_names(fn):
        """Local names assigned a dict/list literal in this function —
        responses under construction, not shared responses."""
        fresh = set()
        for node in _walk_no_functions(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, (ast.Dict, ast.List, ast.DictComp)
            ):
                fresh.update(
                    t.id for t in node.targets if isinstance(t, ast.Name)
                )
        return fresh

    @staticmethod
    def _base_name(node):
        """Innermost Name a subscript chain hangs off (rendered[0] ->
        'rendered'); None for call results etc."""
        while isinstance(node, ast.Subscript):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    def check(self, tree, lines, path):
        findings = []
        for fn in list(_functions(tree)) + [tree]:
            fresh = self._fresh_dict_names(fn)
            for node in _walk_no_functions(fn):
                if not isinstance(node, ast.Assign):
                    continue
                for target in node.targets:
                    if not (
                        isinstance(target, ast.Subscript)
                        and isinstance(target.slice, ast.Constant)
                        and target.slice.value == "parameters"
                    ):
                        continue
                    if not (
                        isinstance(node.value, ast.Dict)
                        and any(
                            isinstance(v, ast.Constant)
                            and isinstance(v.value, bool)
                            for v in node.value.values
                        )
                    ):
                        continue  # not the marker-stamp shape
                    base = self._base_name(target.value)
                    if base is not None and base in fresh:
                        continue  # freshly built response: nothing to lose
                    findings.append(self.finding(
                        path, lines, node,
                        'marker dict assigned to ["parameters"] replaces '
                        "any response parameters the model set — merge "
                        'with .setdefault("parameters", {})[key] = value',
                    ))
        return findings


@register
class SharedMutRule(Rule):
    """SHARED-MUT — unlocked mutation of state shared with a spawned
    thread.

    For every class that spawns ``threading.Thread(target=self.<m>)``,
    the attributes that thread closure touches are shared state: any
    assignment to them — or in-place collection mutation
    (``self._endpoints.append(...)``, the live-discovery membership
    shape) — from OTHER methods must happen under a lock (lexically
    inside ``with *lock/cv/cond:`` or in a ``*_locked`` method, this
    repo's caller-holds-the-lock convention), or in ``__init__`` before
    the thread can exist.
    """

    id = "SHARED-MUT"
    rationale = (
        "writes racing a scheduler/worker thread corrupt state "
        "invisibly; every cross-thread write needs the lock"
    )

    def _thread_targets(self, cls):
        targets = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            func_text = _expr_text(node.func) or ""
            if not func_text.endswith("Thread"):
                continue
            for kw in node.keywords:
                if kw.arg == "target":
                    text = _expr_text(kw.value) or ""
                    if text.startswith("self."):
                        targets.add(text[len("self."):])
        return targets

    def check(self, tree, lines, path):
        findings = []
        for cls in ast.walk(tree):
            if isinstance(cls, ast.ClassDef):
                findings.extend(self._check_class(cls, lines, path))
        return findings

    def _check_class(self, cls, lines, path):
        methods = {
            node.name: node
            for node in cls.body
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        closure = set()
        frontier = [m for m in self._thread_targets(cls) if m in methods]
        if not frontier:
            return []
        while frontier:
            name = frontier.pop()
            if name in closure:
                continue
            closure.add(name)
            for node in ast.walk(methods[name]):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                    and node.func.attr in methods
                ):
                    frontier.append(node.func.attr)

        shared = set()
        for name in closure:
            for node in ast.walk(methods[name]):
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr not in methods
                ):
                    shared.add(node.attr)
        if not shared:
            return []

        findings = []
        for name, fn in methods.items():
            if name in closure or name == "__init__":
                continue
            if name.endswith("_locked"):
                continue  # caller holds the lock by convention
            locked_nodes = set()
            for node in ast.walk(fn):
                if _is_lockish_with(node):
                    for sub in ast.walk(node):
                        locked_nodes.add(id(sub))
            for node in ast.walk(fn):
                if id(node) in locked_nodes:
                    continue
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    flat = []
                    for t in targets:
                        flat.extend(
                            t.elts if isinstance(t, (ast.Tuple, ast.List))
                            else [t]
                        )
                    for t in flat:
                        if self._is_shared_attr(t, shared):
                            findings.append(self.finding(
                                path, lines, node,
                                f"self.{t.attr} is touched by the "
                                f"{'/'.join(sorted(closure))} thread "
                                f"closure but written here ({name}) "
                                "without the lock",
                            ))
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _COLLECTION_MUTATORS
                    and self._is_shared_attr(node.func.value, shared)
                ):
                    # in-place mutation races the reader exactly like an
                    # assignment: a prober iterating self._endpoints while
                    # update_endpoints appends/removes sees a torn list
                    findings.append(self.finding(
                        path, lines, node,
                        f"self.{node.func.value.attr}."
                        f"{node.func.attr}() mutates state the "
                        f"{'/'.join(sorted(closure))} thread closure "
                        f"reads, here ({name}) without the lock",
                    ))
        return findings

    @staticmethod
    def _is_shared_attr(node, shared):
        """Whether *node* is ``self.<attr>`` for a thread-shared attr."""
        return (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in shared
        )


# callees that fix an array's dispatch shape (bucketing/padding helpers)
_SHAPE_SANITIZER_RE = re.compile(r"(?i)(pad|bucket|chunk)")


@register
class JitUnboundedShapeRule(Rule):
    """JIT-UNBOUNDED-SHAPE — jitted callable invoked with a
    request-shaped array and no bucketing/padding on the path.

    ``jax.jit`` keys executables on input SHAPE: a jitted prefill fed
    ``np.asarray(prompt_tokens).reshape(1, -1)`` compiles a fresh XLA
    program for EVERY distinct prompt length (seconds each on a real
    chip), unbounded by anything but client behavior — the serving-path
    recompile storm serve/lm's geometric bucket set exists to fix (the
    pre-fix ``_admit`` prefill in serve/models/continuous.py).  Within a
    function, a local whose value came through a ragged ``reshape``
    (any ``-1`` dimension — the shape is data-dependent) must pass
    through a shape sanitizer (a ``pad*``/``bucket*``/``chunk*`` call)
    before reaching a jit-bound callable's argument list.
    """

    id = "JIT-UNBOUNDED-SHAPE"
    rationale = (
        "a jitted callable fed a request-shaped array compiles one "
        "executable per distinct length — bucket/pad the shape first "
        "(continuous.py per-prompt-length prefill recompiles)"
    )

    @staticmethod
    def _is_ragged_reshape(node):
        """A ``<expr>.reshape(...)`` call with a -1 dimension (including
        ``reshape((-1,))`` tuple forms): the result's shape follows the
        DATA, not the code."""
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "reshape"):
            return False
        args = []
        for a in node.args:
            args.extend(a.elts if isinstance(a, (ast.Tuple, ast.List))
                        else [a])
        return any(
            isinstance(a, ast.Constant) and a.value == -1 for a in args
        ) or any(
            isinstance(a, ast.UnaryOp) and isinstance(a.op, ast.USub)
            and isinstance(a.operand, ast.Constant)
            and a.operand.value == 1
            for a in args
        )

    @classmethod
    def _is_sanitizer(cls, node):
        if not isinstance(node, ast.Call):
            return False
        text = _expr_text(node.func)
        return bool(
            text and _SHAPE_SANITIZER_RE.search(_last_segment(text))
        )

    def _tainted_names(self, func):
        """Locals whose LAST shaping assignment in *func* is a ragged
        reshape (a later sanitizer assignment clears the taint).
        _walk_no_functions yields statements in reverse source order, so
        assignments are re-sorted by position before last-wins folding."""
        tainted = {}
        assigns = sorted(
            (n for n in _walk_no_functions(func)
             if isinstance(n, ast.Assign)),
            key=lambda n: (n.lineno, n.col_offset),
        )
        for node in assigns:
            targets = [
                _expr_text(t) for t in node.targets
                if _expr_text(t) is not None
            ]
            if not targets:
                continue
            value = node.value
            if self._is_sanitizer(value):
                for t in targets:
                    tainted[t] = False
            elif any(
                self._is_ragged_reshape(sub) for sub in ast.walk(value)
            ):
                for t in targets:
                    tainted[t] = True
        return {name for name, on in tainted.items() if on}

    def _names_outside_sanitizers(self, node):
        """Name/attr texts in *node*, skipping sanitizer-call subtrees
        (``jitfn(pad_prompt(prompt, w))`` is the FIXED shape)."""
        if self._is_sanitizer(node):
            return
        text = _expr_text(node)
        if text is not None:
            yield text
        if isinstance(node, (ast.Name, ast.Attribute)):
            return
        for child in ast.iter_child_nodes(node):
            yield from self._names_outside_sanitizers(child)

    def check(self, tree, lines, path):
        jit_bound = _jit_bound_names(tree)
        if not jit_bound:
            return []
        findings = []
        for func in _functions(tree):
            tainted = self._tainted_names(func)
            if not tainted:
                continue
            for node in _walk_no_functions(func):
                if not isinstance(node, ast.Call):
                    continue
                callee = _expr_text(node.func)
                if callee not in jit_bound:
                    continue
                hit = sorted(
                    name
                    for arg in list(node.args)
                    + [kw.value for kw in node.keywords]
                    for name in self._names_outside_sanitizers(arg)
                    if name in tainted
                )
                if hit:
                    findings.append(self.finding(
                        path, lines, node,
                        f"jit-compiled {callee}() takes "
                        f"{'/'.join(dict.fromkeys(hit))}, whose shape "
                        "follows request data (ragged reshape): one XLA "
                        "compile per distinct length — pad/bucket the "
                        "shape first",
                    ))
        return findings


# Shared with the interprocedural resource engine: one spec vocabulary
# drives this lexical pre-filter, the whole-program rules, and the
# dynamic ResourceWitness (see analysis/resources.py).
from client_tpu.analysis.resources import _REFCOUNT_NAME_RE  # noqa: E402


@register
class BgThreadCrashRule(Rule):
    """BG-THREAD-CRASH — a ``threading.Thread`` loop target with no
    top-level exception guard dies silently and takes its subsystem
    with it.

    Background service threads (probers, gossip loops, accept loops,
    schedulers) are registered once and expected to run forever.  Python
    prints an unhandled thread exception to stderr and simply ends the
    thread — health probing freezes, membership stops updating, the peer
    server goes deaf — with zero errors surfaced to anyone.  This is the
    bug class the endpoint-pool prober fix patched by hand (a malformed
    probe tuple unpacked in the loop body killed all probing forever);
    this rule makes the *shape* illegal instead of the one instance.

    Heuristic: resolve each ``threading.Thread(target=X)`` registration
    to a same-file function (``self.method`` within the class, bare
    names to the class's or module's functions).  Every ``while`` loop
    in the target must either sit inside a ``try`` or have a fully
    guarded body — every top-level statement a ``try``, a trivial
    control statement (``pass``/``break``/``continue``/``return``), or
    an ``if`` composed of those (the ``if stop.wait(t): return`` sleep
    shape).  Bounded ``for`` loops and loop-less targets are exempt: the
    rule is about loops meant to run forever.
    """

    id = "BG-THREAD-CRASH"
    rationale = (
        "an unguarded exception in a background thread's service loop "
        "kills the thread silently — probing/gossip/accept stops forever "
        "with no surfaced error (the endpoint-pool prober-arity incident)"
    )

    @staticmethod
    def _thread_target(call):
        """('self'|'bare', name) for a threading.Thread(target=...)
        registration, else None."""
        if _last_segment(_expr_text(call.func) or "") != "Thread":
            return None
        for kw in call.keywords:
            if kw.arg != "target":
                continue
            value = kw.value
            if (
                isinstance(value, ast.Attribute)
                and isinstance(value.value, ast.Name)
                and value.value.id == "self"
            ):
                return ("self", value.attr)
            if isinstance(value, ast.Name):
                return ("bare", value.id)
        return None

    @classmethod
    def _safe_stmt(cls, stmt):
        if isinstance(stmt, (ast.Try, ast.Pass, ast.Break, ast.Continue,
                             ast.Return)):
            return True
        if isinstance(stmt, ast.If):
            return all(
                cls._safe_stmt(s) for s in stmt.body + stmt.orelse
            )
        return False

    @classmethod
    def _unguarded_loops(cls, fn):
        """``while`` loops in *fn* that are neither under a ``try`` nor
        fully-guarded-bodied (nested defs not crossed)."""
        out = []

        def scan(node, guarded):
            if isinstance(node, ast.Try):
                for child in node.body:
                    scan(child, True)
                for handler in node.handlers:
                    for child in handler.body:
                        scan(child, guarded)
                for child in node.orelse + node.finalbody:
                    scan(child, guarded)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            if isinstance(node, ast.While) and not guarded:
                if not all(cls._safe_stmt(s) for s in node.body):
                    out.append(node)
            for child in ast.iter_child_nodes(node):
                scan(child, guarded)

        for stmt in fn.body:
            scan(stmt, False)
        return out

    def check(self, tree, lines, path):
        findings = []
        module_fns = {
            n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)
        }
        seen = set()

        def visit(node, methods):
            if isinstance(node, ast.ClassDef):
                methods = {
                    f.name: f for f in node.body
                    if isinstance(f, ast.FunctionDef)
                }
            if isinstance(node, ast.Call):
                target = self._thread_target(node)
                if target is not None:
                    kind, name = target
                    fn = methods.get(name)
                    if fn is None and kind == "bare":
                        fn = module_fns.get(name)
                    if fn is not None:
                        for loop in self._unguarded_loops(fn):
                            key = (fn.name, loop.lineno)
                            if key in seen:
                                continue
                            seen.add(key)
                            findings.append(self.finding(
                                path, lines, loop,
                                f"{fn.name}() runs as a thread target "
                                f"(registered at line {node.lineno}) but "
                                "this while loop has no top-level "
                                "exception guard — one escaped exception "
                                "kills the thread silently and its "
                                "subsystem with it; wrap the loop (or "
                                "its whole body) in try/except",
                            ))
            for child in ast.iter_child_nodes(node):
                visit(child, methods)

        visit(tree, {})
        return findings


@register
class RefcountPairRule(Rule):
    """REFCOUNT-PAIR — a class increments a refcount attribute with no
    decrement anywhere in the class.

    The paged KV pool's block sharing (serve/lm/kv.py) lives and dies by
    refcount discipline: ``retain`` adds a reference, ``release`` drops
    one, and a reference that is incremented but never decremented is a
    LEAKED SHARED BLOCK — never freed, never readable, silently shrinking
    the pool until admission backpressure bricks the engine.  The leak is
    invisible in tests that don't drain to zero, which is exactly how it
    ships.

    Heuristic: within one class, an increment of an attribute or mapping
    whose name looks refcount-ish (``refs``, ``_refs``, ``refcount``,
    ``*_refcount``, ``ref_count``) — ``+= 1``-style AugAssign or an
    ``x = <ref> + n`` rebind — must be paired with a decrement of the
    SAME name somewhere in the class (``-=`` or a ``<ref> - n``
    expression on every holder's exit path; the class-level pairing is
    the static floor we can check).  A class that only ever increments
    gets one finding per incrementing method.
    """

    id = "REFCOUNT-PAIR"
    rationale = (
        "a refcount incremented with no paired decrement is a leaked "
        "shared block: the pool shrinks until admission bricks "
        "(serve/lm/kv.py retain/release discipline)"
    )

    @staticmethod
    def _ref_name(node):
        """The refcount-ish name a target/operand refers to, or None.
        Accepts ``self._refs`` (Attribute), ``self._refs[b]`` (Subscript
        over an Attribute/Name) and bare ``refs`` names."""
        if isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Attribute):
            name = node.attr
        elif isinstance(node, ast.Name):
            name = node.id
        else:
            return None
        return name if _REFCOUNT_NAME_RE.search(name) else None

    @classmethod
    def _deltas(cls, fn):
        """(increments, decrements) ref-name sets in one function."""
        incs, decs = {}, set()
        for node in _walk_no_functions(fn):
            if isinstance(node, ast.AugAssign):
                name = cls._ref_name(node.target)
                if name is None:
                    continue
                if isinstance(node.op, ast.Add):
                    incs.setdefault(name, node)
                elif isinstance(node.op, ast.Sub):
                    decs.add(name)
            elif isinstance(node, ast.BinOp):
                # x = self._refs[b] + 1 / left = self._refs[b] - 1 forms
                name = cls._ref_name(node.left)
                if name is None:
                    continue
                if isinstance(node.op, ast.Add):
                    incs.setdefault(name, node)
                elif isinstance(node.op, ast.Sub):
                    decs.add(name)
        return incs, decs

    def check(self, tree, lines, path):
        findings = []
        for cls_node in ast.walk(tree):
            if not isinstance(cls_node, ast.ClassDef):
                continue
            incs = {}   # ref name -> [(method, witness node), ...]
            decs = set()
            for fn in _functions(cls_node):
                fn_incs, fn_decs = self._deltas(fn)
                for name, node in fn_incs.items():
                    incs.setdefault(name, []).append((fn.name, node))
                decs.update(fn_decs)
            for name, sites in sorted(incs.items()):
                if name in decs:
                    continue
                for method, node in sites:
                    findings.append(self.finding(
                        path, lines, node,
                        f"{method}() increments {name} but class "
                        f"{cls_node.name} never decrements it — a "
                        "leaked reference is a block the pool can "
                        "neither free nor read; pair every retain "
                        "with a release on each holder's exit path",
                    ))
        return findings


# Span vocabulary also lives in the resource spec table: explicit
# span/timer starters (any receiver) + the tracers' sample(), and the
# calls that end a started span's lifetime (receiver = the span, or the
# span passed as an argument: trace.close() / tracer.complete(trace)).
from client_tpu.analysis.resources import (  # noqa: E402
    _SPAN_FINISH_METHODS,
    _SPAN_START_METHODS,
    _TRACERISH_RE,
)


@register
class SpanLeakRule(Rule):
    """SPAN-LEAK — a span/timer started without a finish on every exit
    path.

    The tracing layer's contract is that every sampled span COMPLETES:
    completion is what appends the record to the bounded deque and the
    trace file.  A span started (``tracer.sample(...)``, ``start_span``,
    ``start_timer``) whose finish (``complete``/``finish``/``close``/
    ``end``/``stop``) is not inside a ``finally`` leaks the moment any
    statement between start and finish raises — the request happened,
    the timeline says it didn't, and the flight recorder's ring (fed by
    the completion hook) has a hole exactly where the postmortem needs
    it.  Every tracing bracket in this repo is a ``try/finally`` or a
    context manager for this reason; the rule freezes that shape.

    Heuristic, per function: an assignment ``x = <tracer-ish>.sample(...)``
    (or any ``*.start_span/begin_span/start_timer(...)``) must be paired
    with a finish call on ``x`` that sits inside a ``finally`` block.  A
    span that ESCAPES the function — returned, yielded, stored on an
    attribute, or handed to another call — transfers ownership and is
    exempt (the frontends sample, then complete in their own finally).
    """

    id = "SPAN-LEAK"
    rationale = (
        "a span started without a finish on every exit path (try/finally "
        "or context manager) vanishes from the trace file and the flight "
        "recorder exactly when a failure makes it interesting — the "
        "timeline hole the tracing brackets exist to prevent"
    )

    @classmethod
    def _start_call(cls, node):
        """The span-starting Call inside an assignment value, or None."""
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if not isinstance(fn, ast.Attribute):
                continue
            if fn.attr in _SPAN_START_METHODS:
                return sub
            if fn.attr == "sample":
                recv = _expr_text(fn.value)
                if recv and _TRACERISH_RE.search(_last_segment(recv)):
                    return sub
        return None

    @staticmethod
    def _uses_name(node, name):
        return any(
            isinstance(sub, ast.Name) and sub.id == name
            for sub in ast.walk(node)
        )

    @classmethod
    def _classify(cls, fn, name, start_assign):
        """(finishes, protected_finishes, escapes) of span var *name*."""
        finishes = []
        protected = []
        escaped = False
        final_nodes = set()
        for sub in _walk_no_functions(fn):
            if isinstance(sub, ast.Try):
                for stmt in sub.finalbody:
                    final_nodes.update(id(n) for n in ast.walk(stmt))
        for sub in _walk_no_functions(fn):
            if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
                if sub.value is not None and cls._uses_name(sub.value, name):
                    escaped = True
            elif isinstance(sub, ast.Assign) and sub is not start_assign:
                # self._trace = x: stored; finished elsewhere
                if cls._uses_name(sub.value, name) and any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in sub.targets
                ):
                    escaped = True
            elif isinstance(sub, ast.Call):
                fn_expr = sub.func
                is_finish = (
                    isinstance(fn_expr, ast.Attribute)
                    and fn_expr.attr in _SPAN_FINISH_METHODS
                    and (
                        cls._uses_name(fn_expr.value, name)
                        or any(cls._uses_name(a, name) for a in sub.args)
                    )
                )
                if is_finish:
                    finishes.append(sub)
                    if id(sub) in final_nodes:
                        protected.append(sub)
                elif any(
                    cls._uses_name(a, name) for a in sub.args
                ) or any(
                    kw.value is not None and cls._uses_name(kw.value, name)
                    for kw in sub.keywords
                ):
                    # handed to another callable: ownership transferred
                    # (the callee finishing it is beyond a per-file pass)
                    escaped = True
        return finishes, protected, escaped

    def check(self, tree, lines, path):
        findings = []
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in _walk_no_functions(fn):
                if not isinstance(node, ast.Assign):
                    continue
                if len(node.targets) != 1 or not isinstance(
                    node.targets[0], ast.Name
                ):
                    continue
                start = self._start_call(node.value)
                if start is None:
                    continue
                name = node.targets[0].id
                finishes, protected, escaped = self._classify(
                    fn, name, node
                )
                if escaped:
                    continue
                if not finishes:
                    findings.append(self.finding(
                        path, lines, node,
                        f"{fn.name}() starts span {name!r} and never "
                        "finishes it — the sampled request vanishes from "
                        "the trace file; complete it in a try/finally or "
                        "use the context-manager bracket",
                    ))
                elif not protected:
                    findings.append(self.finding(
                        path, lines, node,
                        f"{fn.name}() finishes span {name!r} outside any "
                        "finally block — an exception between start and "
                        "finish leaks the span exactly when the timeline "
                        "matters; move the finish into try/finally or use "
                        "the context-manager bracket",
                    ))
        return findings


# peer-RPC callees whose bound replies the ACK-BEFORE-STORE rule tracks
# (last dotted segment) — the fleet tier's transport verbs
_PEER_REPLY_CALLS = {
    "_peer_call", "peer_call", "_traced_peer_call", "recv_frame",
    "_recv_frame", "_ask",
}
# counter names that read as durability acks (bounded: 'ack' at a word
# boundary so e.g. 'backoff' never matches)
_ACK_NAME_RE = re.compile(r"(?i)(^|_)(n?acks?|acked)(_|$)")


@register
class AckBeforeStoreRule(Rule):
    """ACK-BEFORE-STORE — a peer reply counted as durability unchecked.

    The fleet tier's replicated stores answer every reachable request
    with a frame, and the frame says whether the payload was actually
    STORED (``{"stored": false}`` marks a stale snapshot the peer
    REJECTED).  A quorum/durability counter that increments on the mere
    arrival of a reply counts reachability, not durability: a fleet of
    peers all rejecting a stale snapshot would still 'reach quorum' and
    the client would hold an ack for a step a SIGKILL can lose — the
    exact acks-then-loses fork the write-quorum mode exists to prevent.
    Fires in functions that (a) bind a peer-transport reply, (b) bump
    an ack-named counter, and (c) never consult a ``"stored"`` field.
    Transport-level delivery counters should use a non-ack name
    (``accepted``, ``delivered``); real ack accounting must check
    ``reply.get("stored")``.
    """

    id = "ACK-BEFORE-STORE"
    rationale = (
        "a peer reply is reachability, not durability: acking without "
        "checking the reply's 'stored' field can ack a step every peer "
        "rejected as stale (acks-then-loses)"
    )

    @staticmethod
    def _binds_peer_reply(fn):
        for node in _walk_no_functions(fn):
            calls = ()
            if isinstance(node, ast.Assign):
                calls = ast.walk(node.value)
            elif isinstance(node, ast.For):
                calls = ast.walk(node.iter)
            for sub in calls:
                if isinstance(sub, ast.Call) and _last_segment(
                    _expr_text(sub.func) or ""
                ) in _PEER_REPLY_CALLS:
                    return True
        return False

    @staticmethod
    def _checks_stored(fn):
        for node in _walk_no_functions(fn):
            if isinstance(node, ast.Constant) and node.value == "stored":
                return True
        return False

    def check(self, tree, lines, path):
        findings = []
        for fn in _functions(tree):
            if not self._binds_peer_reply(fn) or self._checks_stored(fn):
                continue
            for node in _walk_no_functions(fn):
                if not (
                    isinstance(node, ast.AugAssign)
                    and isinstance(node.op, ast.Add)
                ):
                    continue
                target = _last_segment(_expr_text(node.target) or "")
                if target and _ACK_NAME_RE.search(target):
                    findings.append(self.finding(
                        path, lines, node,
                        f"{fn.name}() counts a peer reply as ack "
                        f"{target!r} without checking the reply's "
                        "'stored' field — a stale-rejecting peer is "
                        "reachable but is no durability; gate the "
                        "increment on reply.get('stored')",
                    ))
        return findings
