"""Interprocedural concurrency rules over the whole-program call graph.

Three rules run on the :mod:`client_tpu.analysis.callgraph` substrate —
each one encodes the *cross-function* generalization of a hazard this
repo actually shipped (the lexical rules in ``rules.py`` only catch the
single-function shape):

- **LOCK-INV** — lock-order inversion: the global lock-acquisition graph
  (edges ``A -> B`` whenever B is acquired — directly or through any call
  chain — while A is held) contains a cycle.  Two threads walking the
  cycle from different entry points deadlock; no single function ever
  shows both edges.
- **BLOCK-UNDER-LOCK** — the interprocedural LOCK-DISPATCH: any path from
  a ``with lock:`` body (or a ``*_locked`` caller-holds-the-lock method)
  to a blocking operation — jit/device dispatch, ``time.sleep``,
  subprocess/socket/HTTP, a timeout-less ``queue.get``/``cv.wait``/
  ``thread.join`` — through any call depth.  The prefill-under-``_cv``
  incident (ADVICE round 5) was exactly this, three frames below the
  ``with``.
- **CALLBACK-UNDER-LOCK** — user/observer callbacks (metrics observers,
  resolver callables, trace sinks, anything invoked through a parameter
  or ``getattr`` result) reached while a private lock is held.  A
  callback that looks back at the owning object re-enters the lock and
  deadlocks; one that blocks extends the critical section unboundedly.
  This is the re-entrancy vector the balance/frontdoor observer plumbing
  is one refactor away from.
- **PEER-CALL-UNDER-LOCK** — the fleet-tier generalization of
  BLOCK-UNDER-LOCK: a rendezvous/peer RPC (``fleet``/``peer``/
  ``rendezvous``-named receivers, or the frame/gather transport
  primitives) reachable while any engine or pool lock is held.  A peer
  lookup is bounded by timeouts, but "bounded" is still hundreds of
  milliseconds — under the LM engine's ``_cv`` that stalls every decode
  tick behind one slow peer; under the balance pool's lock it stalls
  every route.  The tier's whole surface (even its host-side methods)
  must stay out of critical sections: snapshot under the lock, call the
  tier outside it.

Precision choices (documented FN > noisy FP):

- deferred references (``Thread(target=...)``, lambda bodies) never
  inherit the registering frame's held locks;
- a ``cv.wait()`` under the cv's own lock is the normal condition-variable
  pattern and is exempt — only *other* locks held across the wait flag;
- a ``*_locked`` method's body runs under the pseudo lock
  ``<caller-held:Class>``; pseudo locks flag blocking/callback work but
  never enter the lock-order graph (they have no identity to invert);
- call chains are depth-limited and each call site reports at most one
  finding per rule.
"""

from client_tpu.analysis import locksets
from client_tpu.analysis.core import Finding, ProgramRule, register_program

_MAX_DEPTH = 12
_MAX_EFFECTS = 6  # distinct transitive effects remembered per function


def _fn_key(mod, fn):
    return (mod.module, fn.qualname)


def _chain_text(chain):
    return " -> ".join(chain)


def _effective_held(program, fn, held):
    """The lexical held set plus the *_locked pseudo lock."""
    if fn.requires_lock:
        return list(held) + [program.pseudo_required_lock(fn)]
    return list(held)


def _is_pseudo(lock):
    return lock.startswith("<caller-held:")


# Receiver segments (leading underscores stripped) that mark a call as a
# peer RPC: anything invoked on a fleet tier / peer set / rendezvous
# object.  Deliberately receiver-shaped, not op-shaped — the tier's
# host-side methods ride the same ban (a critical section should not
# even *touch* the tier's surface; snapshot and call outside).
_PEER_RECEIVERS = {"fleet", "peer", "peers", "rendezvous", "rdv"}

# Call names that ARE the fleet/peer surface, whatever the receiver:
# the frame primitives, the rendezvous collective ops, and FleetTier's
# methods.  Needed because the callgraph collapses ``self.fleet.x(...)``
# to a receiver-less ("method", "x") ref — the names carry the signal
# when the receiver text is gone.
_PEER_CALL_NAMES = {
    "all_gather", "all_ranks_stable", "peer_call", "_peer_call",
    "send_frame", "recv_frame", "_send_frame", "_recv_frame",
    "fetch_summary", "prefix_lookup", "cache_lookup", "gossip_now",
    "export_prefix", "local_summary",
}


def _peer_desc(ref):
    """Human-readable description when *ref* is a peer RPC, else None."""
    kind, value = ref
    if kind in ("name", "method", "self"):
        if value in _PEER_CALL_NAMES:
            return (f"self.{value}()" if kind == "self" else value + "()")
        return None
    parts = value.split(".")
    if parts[-1] in _PEER_CALL_NAMES:
        return value + "()"
    for part in parts[:-1]:
        if part.lstrip("_") in _PEER_RECEIVERS:
            return value + "()"
    return None


class _Effects:
    """Memoized transitive effects (blocking ops, callback invocations,
    lock acquisitions) per function."""

    def __init__(self, program):
        self.program = program
        self._blocking = {}
        self._callbacks = {}
        self._acquires = {}
        self._peers = {}

    # Each entry: (desc, kind, waits_on, chain-tuple)
    def blocking(self, mod, fn):
        return self._memo(
            self._blocking, mod, fn,
            direct=lambda f: [
                (b["desc"], b["kind"], b.get("waits_on"), (f.qualname,))
                for b in f.blocking
            ],
            extend=lambda eff, qual: [
                (d, k, w, (qual,) + chain) for d, k, w, chain in eff
            ],
        )

    # Each entry: (desc, chain-tuple)
    def callbacks(self, mod, fn):
        return self._memo(
            self._callbacks, mod, fn,
            direct=lambda f: [
                (c["desc"], (f.qualname,)) for c in f.callbacks
            ],
            extend=lambda eff, qual: [
                (d, (qual,) + chain) for d, chain in eff
            ],
        )

    # Each entry: (desc, chain-tuple) — peer RPCs reachable from fn
    def peer_calls(self, mod, fn):
        return self._memo(
            self._peers, mod, fn,
            direct=lambda f: [
                (desc, (f.qualname,))
                for call in f.calls
                if not call["deferred"]
                and (desc := _peer_desc(call["ref"])) is not None
            ],
            extend=lambda eff, qual: [
                (d, (qual,) + chain) for d, chain in eff
            ],
        )

    # Each entry: (lock, line-of-acquisition, chain-tuple)
    def acquires(self, mod, fn):
        return self._memo(
            self._acquires, mod, fn,
            direct=lambda f: [
                (a["lock"], a["line"], (f.qualname,))
                for a in f.acquisitions
            ],
            extend=lambda eff, qual: [
                (lock, line, (qual,) + chain)
                for lock, line, chain in eff
            ],
        )

    def _memo(self, table, mod, fn, direct, extend, _depth=0):
        key = _fn_key(mod, fn)
        if key in table:
            cached = table[key]
            return cached if cached is not None else []
        if _depth > _MAX_DEPTH:
            return []
        table[key] = None  # cycle guard: recursion contributes nothing new
        out = list(direct(fn))
        for call in fn.calls:
            if call["deferred"]:
                continue
            cmod, cfn = self.program.resolve(
                mod, fn, call["ref"], call["nargs"]
            )
            if cfn is None:
                continue
            sub = self._memo(table, cmod, cfn, direct, extend, _depth + 1)
            out.extend(extend(sub, fn.qualname))
        # dedupe on the effect identity (first chain wins: shortest-first
        # is not guaranteed, but one witness chain per effect is enough)
        seen, unique = set(), []
        for eff in out:
            ident = eff[:-1]
            if ident in seen:
                continue
            seen.add(ident)
            unique.append(eff)
            if len(unique) >= _MAX_EFFECTS:
                break
        table[key] = unique
        return unique


@register_program
class BlockUnderLockRule(ProgramRule):
    """BLOCK-UNDER-LOCK — a blocking operation reachable from a lock-held
    region through any call depth.

    Lexical LOCK-DISPATCH sees a dispatch in the same function as the
    ``with``; this rule follows the call graph, so the prefill dispatched
    three frames below ``with self._cv:`` (the real ADVICE round-5
    incident) is flagged at the call site that carries the lock in.
    Same-function dispatches are left to LOCK-DISPATCH (one finding per
    bug); same-function *host* blocking (sleep/subprocess/socket,
    timeout-less waits on someone else's lock) is this rule's to report.
    """

    id = "BLOCK-UNDER-LOCK"
    rationale = (
        "a blocking call reached under a lock (any call depth) extends "
        "the critical section by seconds — the prefill-under-_cv shape"
    )

    def check_program(self, program):
        effects = _Effects(program)
        findings = []
        for mod, fn in program.iter_functions():
            # direct blocking ops under a held lock (non-dispatch: the
            # lexical LOCK-DISPATCH rule owns same-function dispatches)
            for b in fn.blocking:
                held = _effective_held(program, fn, b["held"])
                if not held or b["kind"] == "dispatch":
                    continue
                offending = [
                    lock for lock in held if lock != b.get("waits_on")
                ]
                if not offending:
                    continue
                findings.append(Finding(
                    self.id, mod.path, b["line"], b["col"],
                    f"{b['desc']} blocks while holding "
                    f"{self._locks(offending)} (in {fn.qualname})", "",
                ))
            # blocking ops reached through calls made under a held lock
            for call in fn.calls:
                if call["deferred"]:
                    continue
                held = _effective_held(program, fn, call["held"])
                if not held:
                    continue
                cmod, cfn = program.resolve(
                    mod, fn, call["ref"], call["nargs"]
                )
                if cfn is None:
                    continue
                for desc, kind, waits_on, chain in effects.blocking(
                    cmod, cfn
                ):
                    offending = [
                        lock for lock in held if lock != waits_on
                    ]
                    if not offending:
                        continue
                    findings.append(Finding(
                        self.id, mod.path, call["line"], call["col"],
                        f"call chain {fn.qualname} -> "
                        f"{_chain_text(chain)} reaches blocking {desc} "
                        f"while {self._locks(offending)} is held — move "
                        "the blocking work outside the critical section",
                        "",
                    ))
                    break  # one finding per call site
        return findings

    @staticmethod
    def _locks(locks):
        return ", ".join(sorted(locks))


@register_program
class CallbackUnderLockRule(ProgramRule):
    """CALLBACK-UNDER-LOCK — observer/user callbacks invoked (at any call
    depth) while a private lock is held.

    The callback is code this module does not control: if it looks back
    at the owning object it re-enters the held lock (deadlock on a plain
    Lock, state corruption on an RLock); if it blocks, every waiter on
    the lock stalls behind third-party code.  Deliver snapshots outside
    the lock instead (the pool/breaker ``_SerialDeliverer`` pattern).
    """

    id = "CALLBACK-UNDER-LOCK"
    rationale = (
        "an observer callback under a private lock re-enters or blocks "
        "the lock from third-party code (deliver outside the lock)"
    )

    def check_program(self, program):
        effects = _Effects(program)
        findings = []
        for mod, fn in program.iter_functions():
            for cb in fn.callbacks:
                held = _effective_held(program, fn, cb["held"])
                if not held:
                    continue
                findings.append(Finding(
                    self.id, mod.path, cb["line"], cb["col"],
                    f"callback {cb['desc']} invoked while holding "
                    f"{', '.join(sorted(held))} (in {fn.qualname}) — "
                    "snapshot under the lock, call back outside it", "",
                ))
            for call in fn.calls:
                if call["deferred"]:
                    continue
                held = _effective_held(program, fn, call["held"])
                if not held:
                    continue
                cmod, cfn = program.resolve(
                    mod, fn, call["ref"], call["nargs"]
                )
                if cfn is None:
                    continue
                for desc, chain in effects.callbacks(cmod, cfn):
                    findings.append(Finding(
                        self.id, mod.path, call["line"], call["col"],
                        f"call chain {fn.qualname} -> "
                        f"{_chain_text(chain)} invokes callback {desc} "
                        f"while {', '.join(sorted(held))} is held — "
                        "deliver outside the lock", "",
                    ))
                    break  # one finding per call site
        return findings


@register_program
class PeerCallUnderLockRule(ProgramRule):
    """PEER-CALL-UNDER-LOCK — a rendezvous/peer RPC reachable (at any
    call depth) while an engine or pool lock is held.

    The fleet-tier generalization of BLOCK-UNDER-LOCK: peer lookups are
    timeout-bounded, so the blocking classifier does not see them — but
    hundreds of milliseconds under the LM engine's ``_cv`` stalls every
    decode tick, and under the balance pool's lock stalls every route.
    Detection is receiver-shaped (calls on ``fleet``/``peer``/
    ``rendezvous``-named objects) plus the transport primitives
    (``send_frame``/``recv_frame``/``all_gather``/...), so the rule works
    on fixtures and unresolvable call targets alike.  The whole tier
    surface is banned under locks — host-side methods included — because
    the correct shape is always the same: snapshot under the lock, call
    the tier after releasing it (serve/lm/engine.py's submit/export
    paths are the reference implementation).
    """

    id = "PEER-CALL-UNDER-LOCK"
    rationale = (
        "a peer/rendezvous RPC under an engine or pool lock stalls every "
        "waiter behind one slow peer's timeout — snapshot under the "
        "lock, call the peer outside it"
    )

    def check_program(self, program):
        effects = _Effects(program)
        findings = []
        for mod, fn in program.iter_functions():
            for call in fn.calls:
                if call["deferred"]:
                    continue
                held = _effective_held(program, fn, call["held"])
                if not held:
                    continue
                locks = ", ".join(sorted(held))
                desc = _peer_desc(call["ref"])
                if desc is not None:
                    findings.append(Finding(
                        self.id, mod.path, call["line"], call["col"],
                        f"peer RPC {desc} invoked while holding {locks} "
                        f"(in {fn.qualname}) — snapshot under the lock, "
                        "call the peer outside it", "",
                    ))
                    continue
                cmod, cfn = program.resolve(
                    mod, fn, call["ref"], call["nargs"]
                )
                if cfn is None:
                    continue
                for peer_desc, chain in effects.peer_calls(cmod, cfn):
                    findings.append(Finding(
                        self.id, mod.path, call["line"], call["col"],
                        f"call chain {fn.qualname} -> "
                        f"{_chain_text(chain)} reaches peer RPC "
                        f"{peer_desc} while {locks} is held — move the "
                        "peer call outside the critical section", "",
                    ))
                    break  # one finding per call site
        return findings


@register_program
class LocksetRaceRule(ProgramRule):
    """LOCKSET-RACE — Eraser-style per-field lockset inference across
    thread roots (see :mod:`client_tpu.analysis.locksets`).

    SHARED-MUT is lexical and per-file: it flags an unlocked assignment
    in the same class that spawns the thread.  This rule intersects the
    *candidate guard sets* of every access to a shared field, carried
    interprocedurally: a field written under ``self._lock`` in one
    method and read lock-free from a background thread three calls away
    — or written under lock A while the loop reads under lock B — has an
    empty candidate set and is flagged with both witness sites (file:
    line, holding set, thread-root chain).  Exemptions keep the gate
    honest: ``__init__`` writes (virgin state), single-root fields,
    fields frozen after construction, event/queue/thread handle fields,
    and anything vouched for by the ``*_locked`` caller-holds-the-lock
    convention.  The dynamic twin (``RaceWitness``, armed by
    ``TPULINT_RACE_WITNESS=1``) runs the same algorithm against the real
    held-lock stack at runtime.
    """

    id = "LOCKSET-RACE"
    rationale = (
        "a shared field whose accesses share no common lock across "
        "thread roots is a data race — the Eraser lockset invariant"
    )

    def check_program(self, program):
        findings = []
        for report in locksets.analyze(program):
            findings.append(Finding(
                self.id, report.write.path, report.write.line,
                report.write.col, report.message(), "",
            ))
        return findings


@register_program
class LockInversionRule(ProgramRule):
    """LOCK-INV — lock-order inversion over the global acquisition graph.

    Edge ``A -> B``: somewhere in the program lock B is acquired (in the
    same function or through any call chain) while A is held.  A cycle
    means two threads entering from different points can each hold one
    lock and wait for the other — the textbook deadlock no per-function
    rule can see, because each edge lives in a different function (often
    a different file).  Pseudo (``*_locked``) locks are excluded: they
    have no independent identity to invert.  Re-acquiring the same lock
    is not an inversion (RLock re-entry / imprecise aliasing), so
    self-edges are dropped.
    """

    id = "LOCK-INV"
    rationale = (
        "a cycle in the program-wide lock-acquisition order means two "
        "threads can deadlock holding one lock each"
    )

    def check_program(self, program):
        effects = _Effects(program)
        # (a, b) -> (path, line, via) witness of the first sighting
        edges = {}

        def add_edge(a, b, path, line, via):
            if a == b or _is_pseudo(a) or _is_pseudo(b):
                return
            if (a, b) not in edges:
                edges[(a, b)] = (path, line, via)

        for mod, fn in program.iter_functions():
            for acq in fn.acquisitions:
                for held in acq["held"]:
                    add_edge(
                        held, acq["lock"], mod.path, acq["line"],
                        fn.qualname,
                    )
            for call in fn.calls:
                if call["deferred"] or not call["held"]:
                    continue
                cmod, cfn = program.resolve(
                    mod, fn, call["ref"], call["nargs"]
                )
                if cfn is None:
                    continue
                for lock, _line, chain in effects.acquires(cmod, cfn):
                    for held in call["held"]:
                        add_edge(
                            held, lock, mod.path, call["line"],
                            f"{fn.qualname} -> {_chain_text(chain)}",
                        )

        return [
            self._cycle_finding(cycle, edges)
            for cycle in self._cycles(edges)
        ]

    @staticmethod
    def _cycles(edges):
        """Canonicalized simple cycles in the edge graph (one per cycle
        regardless of entry point), shortest first."""
        graph = {}
        for a, b in edges:
            graph.setdefault(a, []).append(b)
        seen, cycles = set(), []

        def walk(start, node, path, visited):
            for nxt in graph.get(node, ()):
                if nxt == start and len(path) >= 2:
                    rotation = min(range(len(path)), key=path.__getitem__)
                    canon = tuple(path[rotation:] + path[:rotation])
                    if canon not in seen:
                        seen.add(canon)
                        cycles.append(list(canon))
                elif nxt not in visited and len(path) < 8:
                    walk(start, nxt, path + [nxt], visited | {nxt})

        for start in sorted(graph):
            walk(start, start, [start], {start})
        cycles.sort(key=len)
        return cycles

    def _cycle_finding(self, cycle, edges):
        parts = []
        first = None
        for i, a in enumerate(cycle):
            b = cycle[(i + 1) % len(cycle)]
            path, line, via = edges[(a, b)]
            if first is None:
                first = (path, line)
            parts.append(f"{a} -> {b} [{path}:{line} in {via}]")
        path, line = first
        order = " ; ".join(parts)
        return Finding(
            self.id, path, line, 0,
            "lock-order inversion: " + " -> ".join(cycle + [cycle[0]])
            + f" — acquisition edges: {order}; pick one global order",
            "",
        )
