"""Whole-program concurrency model: call graph + per-function lock summaries.

The lexical rules in ``rules.py`` see one function at a time; every
cross-function concurrency hazard this repo shipped (prefill dispatched
under the scheduler ``_cv`` three frames below the ``with``, observer
callbacks fired under a delivery lock) was invisible to them.  This module
builds the interprocedural substrate the ``concurrency.py`` rules run on:

- a **module summary** per file: top-level functions, classes (methods,
  base names, lock-kind attributes, jit-bound attributes), import aliases;
- a **function summary** per function/method/nested def: the lock
  *acquisitions* it performs (each with the locks already held at that
  point), the *blocking operations* it performs (device dispatch, sleeps,
  timeout-less waits/joins/queue gets, sockets/subprocess), the *dynamic
  callback invocations* it makes (observer/callback-shaped attribute
  calls, calls through parameters or ``getattr`` results), its outgoing
  *call edges*, and its **shared-field accesses** — ``self.``-rooted
  reads/writes at up-to-two-segment path granularity (``kv`` vs
  ``kv.pools``), each classified rebind vs interior mutation (*deep*)
  and bare reference load vs interior observation, the raw material the
  lockset pass (``locksets.py``) intersects — every event stamped with
  the lock set lexically held where it happens;
- a **program** index that resolves call references class/module-aware:
  ``self.method()`` through the class and its resolvable bases, bare and
  dotted names through module scope and import aliases, constructor calls
  to ``__init__``, plus a unique-method fallback (``obj.take_first()``
  resolves when exactly one class in the program defines an
  arity-compatible ``take_first``), and callback registration points
  (``threading.Thread(target=...)``, lambda bodies) as *deferred*
  references that never inherit the registering frame's held locks.

Lock identity is ``Class.attr`` for ``self.<attr>`` locks, ``module.name``
for module-level locks, and ``module::func.name`` for function-locals —
stable across files so the lock-order graph composes program-wide.  A
``*_locked`` method (this repo's caller-holds-the-lock convention) is
summarized as *requiring* a lock on entry; rules model that as a pseudo
lock (``<caller-held:Class>``) held across its body.

Held-lock tracking is lexical: ``with lock:`` bodies extend the held set;
a bare ``.acquire()`` records the acquisition event (it feeds the
lock-order graph) but does not extend the held set for the statements
after it — the approximation the rules document.

Everything here is serializable plain data (see ``to_dict``/``from_dict``)
so the incremental cache can persist summaries keyed on file mtime and
skip re-parsing unchanged files entirely.
"""

import ast
import os
import re

from client_tpu.analysis import resources as _res
from client_tpu.analysis.rules import (
    _CVLIKE_RE,
    _DISPATCH_FULL,
    _DISPATCH_HINTS,
    _LOCKISH_RE,
    _expr_text,
    _jit_bound_names,
    _last_segment,
)

# Lock-object constructors, by dotted callee text -> kind.
_LOCK_CTORS = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
}
_SEM_CTORS = {"threading.Semaphore", "threading.BoundedSemaphore",
              "Semaphore", "BoundedSemaphore"}

# Blocking callees by full dotted text.
_BLOCKING_FULL = {
    "time.sleep": "time.sleep()",
    "os.system": "os.system()",
    "subprocess.run": "subprocess.run()",
    "subprocess.call": "subprocess.call()",
    "subprocess.check_output": "subprocess.check_output()",
    "subprocess.check_call": "subprocess.check_call()",
    "socket.create_connection": "socket.create_connection()",
    "urllib.request.urlopen": "urlopen()",
}
_BLOCKING_PREFIXES = ("requests.",)

# Attribute calls on receivers whose LAST segment matches these are
# callback invocations (user/observer code this module does not control).
_CALLBACKISH_RECV_RE = re.compile(
    r"(?i)(^|_)(observer|callback|listener|hook|sink)s?$"
)
_CALLBACKISH_ATTR_RE = re.compile(r"(?i)^(on_[a-z0-9_]+|callback|_callback)$")
# Parameter names whose calls count as foreign-code callbacks.  Narrow on
# purpose: a `pred`/`key` predicate parameter is an internal control knob,
# not user code — flagging it under a lock would drown the gate.
_CALLBACKISH_PARAM_RE = re.compile(
    r"(?i)(^|_)(callback|cb|observer|listener|hook|sink|handler|notify"
    r"|on_[a-z0-9_]+)s?$"
)
_EVENTISH_RE = re.compile(r"(?i)(^|_)(event|ev|done|ready|stop|closed)s?$")
_QUEUEISH_RE = re.compile(r"(?i)(^|_)(q|queue|backlog|inbox|outbox)s?$")
_THREADISH_RE = re.compile(r"(?i)(^|_)(thread|prober|worker|pump)s?$")

# In-place mutators: a method call on a self-field through one of these
# names mutates the field's object — for the lockset pass that is a
# *write* access (the discovery-membership shape), not a read.
_MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "remove",
    "pop", "popleft", "popitem", "clear", "discard", "add", "update",
    "setdefault", "sort", "reverse",
}


def module_name_for(path):
    """Dotted module name for *path*.

    Cross-module resolution joins :class:`ModuleSummary.module` against
    the names ``import`` statements use, so the identity must come out
    the same however the scan root was spelled — an absolute CI path
    (``/ci/checkout/client_tpu/...``) and a relative dev path must name
    the same module.  For files inside a package we therefore walk up
    through ``__init__.py`` markers and name the module relative to the
    package root; files outside any package keep the path-derived (but
    still unique) fallback."""
    norm = os.path.normpath(path)
    base = os.path.basename(norm)
    if base.endswith(".py"):
        base = base[:-3]
    directory = os.path.dirname(os.path.abspath(norm))
    if os.path.isfile(os.path.join(directory, "__init__.py")):
        parts = [base]
        d = directory
        while os.path.isfile(os.path.join(d, "__init__.py")):
            d, tail = os.path.split(d)
            if not tail:
                break
            parts.insert(0, tail)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)
    if norm.endswith(".py"):
        norm = norm[:-3]
    parts = [
        p for p in norm.replace(os.sep, "/").split("/") if p not in ("", ".")
    ]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or norm


class FunctionSummary:
    """One function's concurrency-relevant behavior, as plain data."""

    __slots__ = ("qualname", "name", "cls", "line", "requires_lock",
                 "params_min", "params_max", "acquisitions", "calls",
                 "blocking", "callbacks", "accesses", "resources",
                 "res_facts",
                 # scanner scratch (never serialized)
                 "_param_names", "_getattr_locals", "_access_seen")

    def __init__(self, qualname, name, cls, line, requires_lock,
                 params_min, params_max):
        self.qualname = qualname
        self.name = name
        self.cls = cls  # enclosing class name or None
        self.line = line
        self.requires_lock = requires_lock  # the *_locked convention
        self.params_min = params_min
        self.params_max = params_max  # None = *args/**kwargs
        # [{"lock", "line", "col", "held": [...]}]
        self.acquisitions = []
        # [{"ref": (kind, value), "line", "col", "held": [...],
        #   "nargs", "deferred": bool}]
        self.calls = []
        # [{"desc", "kind", "line", "col", "held": [...], "waits_on"}]
        self.blocking = []
        # [{"desc", "line", "col", "held": [...]}]
        self.callbacks = []
        # shared-field accesses (lockset pass): one entry per distinct
        # (attr, kind, held) triple — [{"attr", "kind": "read"|"write",
        # "line", "col", "held": [...]}]
        self.accesses = []
        # resource handle records (lifecycle pass, see resources.py):
        # one entry per acquisition site / wrapper-call binding, each
        # carrying its branch-arm context, the ops/arg-passes performed
        # on the handle, and how (if at all) ownership escaped
        self.resources = []
        # function-level ownership facts: {"returns", "ret_calls",
        # "params", "exits"} — what the interprocedural transfer
        # resolution reads from the CALLEE side
        self.res_facts = {}

    def to_dict(self):
        return {
            "qualname": self.qualname, "name": self.name, "cls": self.cls,
            "line": self.line, "requires_lock": self.requires_lock,
            "params_min": self.params_min, "params_max": self.params_max,
            "acquisitions": self.acquisitions,
            "calls": [dict(c, ref=list(c["ref"])) for c in self.calls],
            "blocking": self.blocking, "callbacks": self.callbacks,
            "accesses": self.accesses,
            "resources": self.resources, "res_facts": self.res_facts,
        }

    @classmethod
    def from_dict(cls, d):
        fn = cls(d["qualname"], d["name"], d["cls"], d["line"],
                 d["requires_lock"], d["params_min"], d["params_max"])
        fn.acquisitions = d["acquisitions"]
        fn.calls = [dict(c, ref=tuple(c["ref"])) for c in d["calls"]]
        fn.blocking = d["blocking"]
        fn.callbacks = d["callbacks"]
        fn.accesses = d.get("accesses", [])
        fn.resources = d.get("resources", [])
        fn.res_facts = d.get("res_facts", {})
        return fn


class ModuleSummary:
    """One file's classes/functions/imports, as plain data."""

    __slots__ = ("path", "module", "imports", "classes", "functions",
                 "toplevel", "module_locks", "jit_names")

    def __init__(self, path, module):
        self.path = path
        self.module = module
        self.imports = {}       # alias -> "module" or "module:attr"
        self.classes = {}       # name -> {"bases": [...], "methods": [...],
        #                                  "lock_attrs": {attr: kind},
        #                                  "sem_attrs": [...],
        #                                  "jit_attrs": [...]}
        self.functions = {}     # qualname -> FunctionSummary
        self.toplevel = []      # top-level function names
        self.module_locks = {}  # module-level lock name -> kind
        self.jit_names = []     # module/self-level names bound from jax.jit

    def to_dict(self):
        return {
            "path": self.path, "module": self.module,
            "imports": self.imports, "classes": self.classes,
            "functions": {q: f.to_dict() for q, f in self.functions.items()},
            "toplevel": self.toplevel, "module_locks": self.module_locks,
            "jit_names": self.jit_names,
        }

    @classmethod
    def from_dict(cls, d):
        ms = cls(d["path"], d["module"])
        ms.imports = d["imports"]
        ms.classes = d["classes"]
        ms.functions = {
            q: FunctionSummary.from_dict(f)
            for q, f in d["functions"].items()
        }
        ms.toplevel = d["toplevel"]
        ms.module_locks = d["module_locks"]
        ms.jit_names = d["jit_names"]
        return ms


# -- summary construction ----------------------------------------------------


def _ctor_kind(call):
    text = _expr_text(call.func) or ""
    if text in _LOCK_CTORS:
        return _LOCK_CTORS[text]
    if text in _SEM_CTORS:
        return "semaphore"
    return None


def _collect_imports(tree, module):
    imports = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    imports[alias.asname] = alias.name
                else:
                    root = alias.name.split(".", 1)[0]
                    imports[root] = root
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                parts = module.split(".")
                parts = parts[: len(parts) - node.level]
                base = ".".join(
                    parts + ([node.module] if node.module else [])
                )
            for alias in node.names:
                if alias.name == "*":
                    continue
                name = alias.asname or alias.name
                imports[name] = f"{base}:{alias.name}"
    return imports


def _direct_nested(fn_node):
    """Immediate nested function defs (not crossing deeper functions)."""
    out = []
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(node)
            continue
        stack.extend(ast.iter_child_nodes(node))
    return sorted(out, key=lambda n: n.lineno)


class _FunctionScanner:
    """Walk one function body with a lexical held-lock stack."""

    def __init__(self, modsum, cls_name, fn_summary, local_locks):
        self.mod = modsum
        self.cls = cls_name
        self.fn = fn_summary
        self.local_locks = local_locks  # local name -> kind
        self._lambda_depth = 0

    # -- lock identity -------------------------------------------------------

    def lock_id(self, text):
        """Stable program-wide identity for a lock expression, or None."""
        if not text:
            return None
        if text.startswith("self."):
            rest = text[len("self."):]
            owner = self.cls or self.mod.module
            return f"{owner}.{rest}"
        if "." not in text:
            if text in self.local_locks:
                return f"{self.mod.module}::{self.fn.qualname}.{text}"
            if text in self.mod.module_locks:
                return f"{self.mod.module}.{text}"
            return f"{self.mod.module}::{self.fn.qualname}.{text}"
        return f"{self.mod.module}:{text}"

    def _is_lockish(self, text):
        if not text:
            return False
        last = _last_segment(text)
        if _LOCKISH_RE.search(last):
            return True
        if text.startswith("self.") and self.cls:
            attrs = self.mod.classes.get(self.cls, {}).get("lock_attrs", {})
            return text[len("self."):] in attrs
        return text in self.local_locks or text in self.mod.module_locks

    # -- classification ------------------------------------------------------

    def _is_jit_bound(self, text):
        if text in self.mod.jit_names:
            return True
        if text.startswith("self.") and self.cls:
            jit_attrs = self.mod.classes.get(self.cls, {}).get("jit_attrs", [])
            return text[len("self."):] in jit_attrs
        return False

    @staticmethod
    def _call_timeout(call, pos_index):
        """True when the call carries a timeout (kw or positional slot)."""
        if any(kw.arg == "timeout" for kw in call.keywords):
            return True
        return len(call.args) > pos_index

    def _classify_blocking(self, call, text):
        """(desc, kind, waits_on) for a blocking call, else None."""
        if text in _BLOCKING_FULL:
            return _BLOCKING_FULL[text], "host", None
        if text and text.startswith(_BLOCKING_PREFIXES):
            return f"{text}()", "host", None
        if self._is_jit_bound(text):
            return f"jit-compiled {text}()", "dispatch", None
        if text in _DISPATCH_FULL:
            return f"{text}()", "dispatch", None
        if text and _last_segment(text) in _DISPATCH_HINTS:
            return f"device-dispatch {text}()", "dispatch", None
        if not isinstance(call.func, ast.Attribute):
            return None
        attr = call.func.attr
        recv = _expr_text(call.func.value)
        if recv is None:
            return None
        last = _last_segment(recv)
        if attr in ("wait", "wait_for"):
            pos = 0 if attr == "wait" else 1
            if self._call_timeout(call, pos):
                return None  # bounded wait
            if _CVLIKE_RE.search(last) or self._is_lockish(recv):
                return (f"{recv}.{attr}()", "cv-wait", self.lock_id(recv))
            if _EVENTISH_RE.search(last):
                return f"{recv}.{attr}()", "event-wait", None
            return None
        if attr == "get" and _QUEUEISH_RE.search(last):
            kwargs = {kw.arg for kw in call.keywords}
            if "timeout" in kwargs or len(call.args) >= 2:
                return None
            for a in call.args[:1]:
                if isinstance(a, ast.Constant) and a.value is False:
                    return None  # non-blocking get
            for kw in call.keywords:
                if kw.arg == "block" and isinstance(
                    kw.value, ast.Constant
                ) and kw.value.value is False:
                    return None
            return f"{recv}.get()", "queue-get", None
        if attr == "join" and not call.args and not any(
            kw.arg == "timeout" for kw in call.keywords
        ):
            if _THREADISH_RE.search(last) or last in ("t", "th"):
                return f"{recv}.join()", "thread-join", None
        if attr == "acquire":
            if self._is_semaphore(recv) and not self._call_timeout(call, 1):
                return f"{recv}.acquire()", "semaphore", None
        return None

    def _is_semaphore(self, text):
        if text.startswith("self.") and self.cls:
            sems = self.mod.classes.get(self.cls, {}).get("sem_attrs", [])
            return text[len("self."):] in sems
        return False

    def _classify_callback(self, call, text):
        """Description for a dynamic callback invocation, else None."""
        func = call.func
        if isinstance(func, ast.Name):
            # calls through callback-named parameters or getattr()-derived
            # locals are dynamic: the callee is caller-supplied code
            if func.id in self.fn._param_names and (
                _CALLBACKISH_PARAM_RE.search(func.id)
            ):
                return f"parameter callback {func.id}()"
            if func.id in self.fn._getattr_locals:
                return f"dynamic callable {func.id}()"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        recv = _expr_text(func.value)
        if recv is None:
            return None
        if _CALLBACKISH_RECV_RE.search(_last_segment(recv)):
            return f"{recv}.{func.attr}()"
        if _CALLBACKISH_ATTR_RE.search(func.attr):
            return f"{recv}.{func.attr}()"
        return None

    # -- shared-field accesses ----------------------------------------------

    def _field_path(self, expr):
        """(path, n_segments) for a ``self.``-rooted expression, or
        (None, 0).  The path keeps up to two segments past ``self`` so
        an owner-confined interior (``kv.pools``) is a distinct variable
        from the shared reference (``kv``) — ``self.kv.pools["k"]``
        accesses ``kv.pools``, ``self.kv.alloc(...)`` accesses ``kv``."""
        text = _expr_text(expr)
        if not text or not text.startswith("self.") or self.cls is None:
            return None, 0
        parts = text.split(".")
        return ".".join(parts[1:3]), len(parts) - 1

    def _field_of(self, expr):
        """The class-field path a ``self.``-rooted expression accesses
        (``self._pending[k].x`` -> ``_pending``), or None."""
        return self._field_path(expr)[0]

    def _record_access(self, attr, kind, node, held, deep=False):
        """Record one shared-field access, deduped per (attr, kind,
        deep, held).  *deep* marks writes that mutate the field's object
        (``self._map[k] = v``, ``self._q.append(x)``) as opposed to a
        pure reference rebind (``self.x = v``) — the lockset pass treats
        consistently guarded rebinds as safe publication (GIL-atomic
        reads) but never interior mutation.

        Lock/semaphore/jit attributes and the class's own methods are
        not data fields; they never enter the access table."""
        if attr is None or self.cls is None:
            return
        base = attr.split(".")[0]
        info = self.mod.classes.get(self.cls, {})
        if (
            base in info.get("lock_attrs", {})
            or base in info.get("sem_attrs", [])
            or base in info.get("jit_attrs", [])
            or base in info.get("methods", [])
        ):
            return
        key = (attr, kind, deep, tuple(held))
        if key in self.fn._access_seen:
            return
        self.fn._access_seen.add(key)
        self.fn.accesses.append({
            "attr": attr, "kind": kind, "deep": deep,
            "line": node.lineno, "col": node.col_offset,
            "held": list(held),
        })

    def _record_target(self, target, held):
        """Record write accesses for an assignment/delete target and walk
        its non-field parts (subscript keys) for the reads they perform."""
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_target(elt, held)
            return
        if isinstance(target, ast.Starred):
            self._record_target(target.value, held)
            return
        if isinstance(target, ast.Attribute):
            attr, nparts = self._field_path(target)
            if attr is not None:
                # self.a = v / self.a.b = v rebind their own path's
                # slot; self.a.b.c = v mutates the a.b object's interior
                self._record_access(attr, "write", target, held,
                                    deep=nparts > 2)
                return
            self._walk(target.value, held)
            return
        if isinstance(target, ast.Subscript):
            attr = self._field_of(target.value)
            if attr is not None:
                # self._map[k] = v mutates the object the field holds —
                # a write at field granularity
                self._record_access(attr, "write", target, held,
                                    deep=True)
            else:
                self._walk(target.value, held)
            self._walk(target.slice, held)
            return
        self._walk(target, held)

    def _call_ref(self, call):
        """Resolvable reference for a call site, or None."""
        func = call.func
        if isinstance(func, ast.Name):
            return ("name", func.id)
        if not isinstance(func, ast.Attribute):
            return None
        text = _expr_text(func)
        if text is None:
            return None
        if text.startswith("self.") and text.count(".") == 1:
            return ("self", func.attr)
        base = text.split(".", 1)[0]
        if base in self.mod.imports or base in self.mod.classes:
            return ("dotted", text)
        return ("method", func.attr)

    # -- the walk ------------------------------------------------------------

    def scan(self, fn_node):
        args = fn_node.args
        names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        if names and names[0] in ("self", "cls"):
            names = names[1:]
        self.fn._param_names = set(names)
        self.fn._getattr_locals = {
            t.id
            for node in ast.walk(fn_node)
            if isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and _expr_text(node.value.func) == "getattr"
            for t in node.targets
            if isinstance(t, ast.Name)
        }
        self.fn._access_seen = set()
        for stmt in fn_node.body:
            self._walk(stmt, ())
        self.fn._param_names = self.fn._getattr_locals = None
        self.fn._access_seen = None

    def _walk(self, node, held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs are summarized separately
        if isinstance(node, ast.Lambda):
            # Split semantics: field ACCESSES keep the current held set
            # (inline combinator lambdas — sorted key=, filter preds —
            # run where they stand, the shape that produced a false
            # race on _resume_step's sort key), while blocking/callback/
            # call EVENTS inside the body record an empty held set as
            # before (a deferred lambda — Thread target, timer callback
            # — runs later on another thread; stamping the registration
            # site's locks onto it would fabricate BLOCK-UNDER-LOCK
            # findings).  _handle_call consults _lambda_depth.
            self._lambda_depth += 1
            self._walk(node.body, held)
            self._lambda_depth -= 1
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                ctx = item.context_expr
                self._walk(ctx, tuple(inner))
                expr = ctx.func if isinstance(ctx, ast.Call) else ctx
                text = _expr_text(expr)
                if text and self._is_lockish(text):
                    lock = self.lock_id(text)
                    self.fn.acquisitions.append({
                        "lock": lock, "line": node.lineno,
                        "col": node.col_offset, "held": list(inner),
                    })
                    if lock not in inner:
                        inner.append(lock)
            for stmt in node.body:
                self._walk(stmt, tuple(inner))
            return
        if isinstance(node, ast.Assign):
            for target in node.targets:
                self._record_target(target, held)
            self._walk(node.value, held)
            return
        if isinstance(node, ast.AugAssign):
            # x += 1 reads and writes the field
            attr = self._field_of(node.target) or (
                self._field_of(node.target.value)
                if isinstance(node.target, ast.Subscript)
                else None
            )
            if attr is not None:
                self._record_access(attr, "read", node.target, held)
            self._record_target(node.target, held)
            self._walk(node.value, held)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._record_target(node.target, held)
                self._walk(node.value, held)
            return
        if isinstance(node, ast.Delete):
            for target in node.targets:
                self._record_target(target, held)
            return
        if isinstance(node, ast.Call):
            self._handle_call(node, held)
            for child in node.args:
                self._walk(child, held)
            for kw in node.keywords:
                self._walk(kw.value, held)
            func = node.func
            # plain Name/dotted-chain callees were fully consumed by
            # _handle_call; anything else (a chained receiver like
            # self._factory().dispatch() or self._map[k].append())
            # still carries calls/accesses in its subtree — walk it
            if not isinstance(func, ast.Name) and (
                not isinstance(func, ast.Attribute)
                or _expr_text(func) is None
            ):
                self._walk(func, held)
            return
        if isinstance(node, ast.Subscript):
            # self.x[i] in load position observes the field's interior
            attr = self._field_of(node.value)
            if attr is not None:
                self._record_access(attr, "read", node, held, deep=True)
            else:
                self._walk(node.value, held)
            self._walk(node.slice, held)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            # iterating a shared field observes (and walks) its interior
            attr = self._field_of(node.iter)
            if attr is not None:
                self._record_access(attr, "read", node.iter, held,
                                    deep=True)
            else:
                self._walk(node.iter, held)
            self._walk(node.target, held)
            for stmt in node.body + node.orelse:
                self._walk(stmt, held)
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in node.generators:
                attr = self._field_of(gen.iter)
                if attr is not None:
                    self._record_access(attr, "read", gen.iter, held,
                                        deep=True)
                else:
                    self._walk(gen.iter, held)
                for cond in gen.ifs:
                    self._walk(cond, held)
            if isinstance(node, ast.DictComp):
                self._walk(node.key, held)
                self._walk(node.value, held)
            else:
                self._walk(node.elt, held)
            return
        if isinstance(node, ast.Attribute):
            # a bare self-rooted chain in load position: a GIL-atomic
            # reference load of its path (deep when it dereferences past
            # the recorded two-segment path)
            attr, nparts = self._field_path(node)
            if attr is not None:
                self._record_access(attr, "read", node, held,
                                    deep=nparts > 2)
                return
            for child in ast.iter_child_nodes(node):
                self._walk(child, held)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, held)

    def _handle_call(self, call, held):
        text = _expr_text(call.func) or ""
        # events inside lambda bodies never inherit the enclosing held
        # set (see the Lambda branch in _walk); field accesses do
        event_held = [] if self._lambda_depth else list(held)
        site = {"line": call.lineno, "col": call.col_offset,
                "held": event_held}
        # a method call THROUGH a field dereferences the receiver: a
        # mutator (self._q.append) writes its interior, anything else is
        # a deep read of it.  self.method() (one segment) is a call
        # edge, not a data access.
        if text.startswith("self.") and text.count(".") >= 2:
            recv, _ = self._field_path(call.func.value)
            if recv is not None:
                kind = (
                    "write" if call.func.attr in _MUTATOR_METHODS
                    else "read"
                )
                self._record_access(recv, kind, call, held, deep=True)
        # callback registration points: the registered callable runs later,
        # on another thread or frame — a deferred edge with no held locks
        if text.endswith("Thread"):
            for kw in call.keywords:
                if kw.arg == "target":
                    ref = self._ref_for_value(kw.value)
                    if ref is not None:
                        self.fn.calls.append({
                            "ref": ref, "line": call.lineno,
                            "col": call.col_offset, "held": [],
                            "nargs": -1, "deferred": True,
                        })
            return
        # explicit lock-method acquisition outside a with-statement
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "acquire"
        ):
            recv = _expr_text(call.func.value)
            if recv and self._is_lockish(recv):
                self.fn.acquisitions.append({
                    "lock": self.lock_id(recv), "line": call.lineno,
                    "col": call.col_offset, "held": event_held,
                })
                return
        blocking = self._classify_blocking(call, text)
        if blocking is not None:
            desc, kind, waits_on = blocking
            self.fn.blocking.append(dict(
                site, desc=desc, kind=kind, waits_on=waits_on,
            ))
            return
        callback = self._classify_callback(call, text)
        if callback is not None:
            self.fn.callbacks.append(dict(site, desc=callback))
            return
        ref = self._call_ref(call)
        if ref is not None:
            nargs = len(call.args) + len(call.keywords)
            self.fn.calls.append(dict(
                site, ref=ref, nargs=nargs, deferred=False,
            ))

    def _ref_for_value(self, value):
        text = _expr_text(value)
        if not text:
            return None
        if text.startswith("self.") and text.count(".") == 1:
            return ("self", text[len("self."):])
        if "." not in text:
            return ("name", text)
        return ("dotted", text)


class _ResourceScanner:
    """Walk one function body collecting resource-handle lifecycles.

    Complements :class:`_FunctionScanner` (which tracks the held-lock
    dimension) with the OWNERSHIP dimension: every acquisition site from
    the registered spec table (``resources.SPECS``) — plus every local
    bound from a resolvable call, a *candidate* whose resource-ness the
    program pass decides through the callee's summary — gets a handle
    record carrying its branch-arm context, the ops/arg-passes performed
    on the handle, and how (if at all) ownership escaped the function.
    Function-level facts (what the function returns freshly acquired,
    which parameters it takes ownership of, its explicit exits) feed the
    callee side of the interprocedural transfer resolution.

    Contexts are "nid:arm" tokens per enclosing if/try/loop arm — the
    branch-arm bookkeeping ``resources.py``'s path algebra consumes.
    """

    def __init__(self, modsum, fn_summary):
        self.mod = modsum
        self.fn = fn_summary
        self.records = []
        self.open = {}        # local name -> its current handle record
        self.params = {}      # param name -> ownership events
        self.exits = []
        self.ret_calls = []
        self.returns = None
        self._param_idx = {}
        self._raises_depth = 0  # inside `with pytest.raises(...)`

    def scan(self, fn_node):
        args = fn_node.args
        pos = args.posonlyargs + args.args
        names = [a.arg for a in pos]
        if names and names[0] in ("self", "cls"):
            names = names[1:]
        self._param_idx = {n: i for i, n in enumerate(names)}
        for stmt in fn_node.body:
            self._stmt(stmt, (), False, ())
        if self.returns is None:
            for rec in self.records:
                if rec["res"] and "returned" in rec["escapes"] and (
                    not rec["in_with"]
                ):
                    self.returns = rec["res"]
                    break
        for rec in self.records:
            # a returned wrapper-call binding chains the returns fact
            if rec["via"] and "returned" in rec["escapes"]:
                self.ret_calls.append(list(rec["via"]))
        self.fn.resources = self.records
        facts = {}
        if self.returns is not None:
            facts["returns"] = self.returns
        if self.ret_calls:
            facts["ret_calls"] = self.ret_calls
        if self.params:
            facts["params"] = self.params
        if self.exits:
            facts["exits"] = self.exits
        self.fn.res_facts = facts

    # -- plumbing ------------------------------------------------------------

    @staticmethod
    def _unwrap(value):
        while isinstance(value, ast.Await):
            value = value.value
        return value

    def _ref(self, call):
        """Resolvable [kind, value] reference for a call, or None
        (list-typed: these are serialized into the summary)."""
        func = call.func
        if isinstance(func, ast.Name):
            return ["name", func.id]
        if not isinstance(func, ast.Attribute):
            return None
        text = _expr_text(func)
        if text is None:
            return None
        if text.startswith("self.") and text.count(".") == 1:
            return ["self", func.attr]
        base = text.split(".", 1)[0]
        if base in self.mod.imports or base in self.mod.classes:
            return ["dotted", text]
        return ["method", func.attr]

    def _open_record(self, var, res, api, via, node, ctx, fin,
                     in_with=False, daemon=False):
        rec = {
            "res": res, "api": api, "via": via, "var": var,
            "line": node.lineno, "col": node.col_offset,
            "ctx": list(ctx), "fin": fin, "in_with": in_with,
            "daemon": daemon, "escapes": [], "ops": [], "passed": [],
        }
        self.records.append(rec)
        if var is not None:
            self._bind(var, rec)
        return rec

    def _bind(self, name, rec):
        """Bind *name* to *rec*.  A rebind in a conditional arm does NOT
        drop earlier records for the name — on the other arm the name
        still refers to the old handle, so later ops/escapes must apply
        to both (``fresh = alloc(); if ...: fresh = alloc(); return
        fresh`` returns either one)."""
        ctx = rec["ctx"]
        kept = [
            r for r in self.open.get(name, ())
            if not _res._unconditional_after(r["ctx"], ctx)
        ]
        kept.append(rec)
        self.open[name] = kept

    def _clear(self, name, ctx):
        """*name* rebound to a non-handle at *ctx*: drop only the
        records the rebind definitely shadows."""
        kept = [
            r for r in self.open.get(name, ())
            if not _res._unconditional_after(r["ctx"], ctx)
        ]
        if kept:
            self.open[name] = kept
        else:
            self.open.pop(name, None)

    def _param_entry(self, name):
        idx = self._param_idx.get(name)
        if idx is None:
            return None
        entry = self.params.get(name)
        if entry is None:
            entry = self.params[name] = {
                "idx": idx, "released": False, "stored": False,
                "passed": [],
            }
        return entry

    def _op(self, name, api, node, ctx, fin):
        for rec in self.open.get(name, ()):
            rec["ops"].append({
                "api": api, "line": node.lineno,
                "col": node.col_offset, "ctx": list(ctx), "fin": fin,
            })

    def _escape(self, value, how):
        """Every tracked/param name inside *value* escapes as *how*."""
        for sub in ast.walk(value):
            if not isinstance(sub, ast.Name):
                continue
            recs = self.open.get(sub.id)
            if recs:
                for rec in recs:
                    if how not in rec["escapes"]:
                        rec["escapes"].append(how)
                continue
            entry = self._param_entry(sub.id)
            if entry is not None and how == "stored":
                entry["stored"] = True

    @staticmethod
    def _daemon_kw(call):
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return False

    @staticmethod
    def _none_guards(test, guards):
        """(then-arm, else-arm) guard sets for an if-test: the arm on
        which a named handle is known None/falsy (so an exit there never
        leaks it — the admission-backpressure idiom)."""
        then_g, else_g = list(guards), list(guards)
        if (
            isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
            and isinstance(test.left, ast.Name)
        ):
            if isinstance(test.ops[0], ast.Is):
                then_g.append(test.left.id)
            elif isinstance(test.ops[0], ast.IsNot):
                else_g.append(test.left.id)
        elif isinstance(test, ast.UnaryOp) and isinstance(
            test.op, ast.Not
        ) and isinstance(test.operand, ast.Name):
            then_g.append(test.operand.id)
        elif isinstance(test, ast.Name):
            else_g.append(test.id)
        return tuple(then_g), tuple(else_g)

    # -- statements ----------------------------------------------------------

    def _stmt(self, node, ctx, fin, guards):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs carry their own summaries
        if isinstance(node, ast.Assign):
            self._assign(node.targets, node.value, node, ctx, fin)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._assign([node.target], node.value, node, ctx, fin)
            return
        if isinstance(node, ast.AugAssign):
            self._expr(node.value, ctx, fin)
            return
        if isinstance(node, ast.Expr):
            self._expr(node.value, ctx, fin, discard=True)
            return
        if isinstance(node, ast.Return):
            self._return(node, ctx, fin, guards)
            return
        if isinstance(node, ast.Raise):
            for child in ast.iter_child_nodes(node):
                self._escape(child, "raised")
                self._expr(child, ctx, fin)
            self.exits.append({
                "kind": "raise", "line": node.lineno,
                "ctx": list(ctx), "guards": list(guards),
            })
            return
        if isinstance(node, ast.If):
            self._expr(node.test, ctx, fin)
            nid = f"if{node.lineno}"
            then_g, else_g = self._none_guards(node.test, guards)
            for stmt in node.body:
                self._stmt(stmt, ctx + (f"{nid}:t",), fin, then_g)
            for stmt in node.orelse:
                self._stmt(stmt, ctx + (f"{nid}:e",), fin, else_g)
            return
        if isinstance(node, ast.While):
            self._expr(node.test, ctx, fin)
            tok = f"loop{node.lineno}:l"
            for stmt in node.body:
                self._stmt(stmt, ctx + (tok,), fin, guards)
            for stmt in node.orelse:
                self._stmt(stmt, ctx, fin, guards)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            if isinstance(node.iter, ast.Name):
                self._op(node.iter.id, "[iterated]", node.iter, ctx, fin)
            else:
                self._expr(node.iter, ctx, fin)
            tok = f"loop{node.lineno}:l"
            for stmt in node.body:
                self._stmt(stmt, ctx + (tok,), fin, guards)
            for stmt in node.orelse:
                self._stmt(stmt, ctx, fin, guards)
            return
        if isinstance(node, ast.Try):
            nid = f"try{node.lineno}"
            for stmt in node.body:
                self._stmt(stmt, ctx + (f"{nid}:b",), fin, guards)
            for i, handler in enumerate(node.handlers):
                for stmt in handler.body:
                    self._stmt(stmt, ctx + (f"{nid}:h{i}",), fin, guards)
            for stmt in node.orelse:
                self._stmt(stmt, ctx + (f"{nid}:o",), fin, guards)
            for stmt in node.finalbody:
                self._stmt(stmt, ctx + (f"{nid}:f",), True, guards)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            raisesctx = False
            for item in node.items:
                ce = self._unwrap(item.context_expr)
                acq = None
                if isinstance(ce, ast.Call):
                    ftext = _expr_text(ce.func) or ""
                    acq = _res.classify_acquire(ftext)
                    last = _last_segment(ftext)
                    if last == "raises" or last.startswith("assertRaises"):
                        raisesctx = True
                if acq is not None:
                    var = (
                        item.optional_vars.id
                        if isinstance(item.optional_vars, ast.Name)
                        else None
                    )
                    self._open_record(var, acq[0], acq[1], None, ce, ctx,
                                      fin, in_with=True)
                    for a in ce.args:
                        self._expr(a, ctx, fin)
                else:
                    self._expr(item.context_expr, ctx, fin)
            self._raises_depth += raisesctx
            for stmt in node.body:
                self._stmt(stmt, ctx, fin, guards)
            self._raises_depth -= raisesctx
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child, ctx, fin, guards)
            elif isinstance(child, ast.expr):
                self._expr(child, ctx, fin)

    def _assign(self, targets, value_node, node, ctx, fin):
        value = self._unwrap(value_node)
        if len(targets) == 1 and isinstance(targets[0], ast.Name):
            name = targets[0].id
            if isinstance(value, ast.Call):
                acq = _res.classify_acquire(
                    _expr_text(value.func) or ""
                )
                if acq is not None:
                    kind, api = acq
                    daemon = (
                        kind == "thread" and self._daemon_kw(value)
                    )
                    for a in value.args:
                        self._expr(a, ctx, fin)
                    for kw in value.keywords:
                        self._expr(kw.value, ctx, fin)
                    self._open_record(name, kind, api, None, node, ctx,
                                      fin, daemon=daemon)
                    return
                callee = self._ref(value)
                self._call(value, ctx, fin)
                if callee is not None:
                    nargs = len(value.args) + len(value.keywords)
                    self._open_record(
                        name, None, _expr_text(value.func) or callee[1],
                        callee + [nargs], node, ctx, fin,
                    )
                else:
                    self._clear(name, ctx)
                return
            if isinstance(value, ast.Name):
                recs = self.open.get(value.id)
                if recs:
                    self.open[name] = list(recs)  # alias: same handles
                    return
            # a tracked handle folded into a composite value (tuple,
            # list concat, slice) now travels under another local our
            # per-name map cannot follow — benefit of the doubt, it
            # escaped (FN over FP)
            self._escape(value_node, "merged")
            self._expr(value_node, ctx, fin)
            self._clear(name, ctx)
            return
        for target in targets:
            if isinstance(target, (ast.Attribute, ast.Subscript,
                                   ast.Tuple, ast.List, ast.Starred)):
                self._escape(value_node, "stored")
                if (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.attr == "daemon"
                ):
                    for rec in self.open.get(target.value.id, ()):
                        rec["daemon"] = not (
                            isinstance(value, ast.Constant)
                            and not value.value
                        )
                self._expr(target, ctx, fin)
        self._expr(value_node, ctx, fin)

    def _return(self, node, ctx, fin, guards):
        value = node.value
        if value is not None:
            v = self._unwrap(value)
            if isinstance(v, ast.Call):
                acq = _res.classify_acquire(_expr_text(v.func) or "")
                if acq is not None:
                    if self.returns is None:
                        self.returns = acq[0]
                else:
                    callee = self._ref(v)
                    if callee is not None:
                        nargs = len(v.args) + len(v.keywords)
                        self.ret_calls.append(
                            [callee[0], callee[1], nargs]
                        )
            self._escape(value, "returned")
            self._expr(value, ctx, fin)
        self.exits.append({
            "kind": "return", "line": node.lineno,
            "ctx": list(ctx), "guards": list(guards),
        })

    # -- expressions ---------------------------------------------------------

    def _expr(self, node, ctx, fin, discard=False):
        if node is None or isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.Await):
            self._expr(node.value, ctx, fin, discard=discard)
            return
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            if node.value is not None:
                self._escape(node.value, "yielded")
                self._expr(node.value, ctx, fin)
            return
        if isinstance(node, ast.Call):
            self._call(node, ctx, fin, discard=discard)
            return
        if isinstance(node, ast.Subscript):
            if isinstance(node.value, ast.Name):
                self._op(node.value.id, "[subscript]", node, ctx, fin)
            else:
                self._expr(node.value, ctx, fin)
            self._expr(node.slice, ctx, fin)
            return
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name):
                self._op(node.value.id, f"[attr {node.attr}]", node,
                         ctx, fin)
            else:
                self._expr(node.value, ctx, fin)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, ctx, fin)

    def _call(self, call, ctx, fin, discard=False):
        func = call.func
        text = _expr_text(func) or ""
        if discard:
            acq = _res.classify_acquire(text)
            if acq is not None and acq[1] == "retain":
                # a standalone retain() increments a reference whose
                # owner lives elsewhere (prefix-trie nodes, an adopting
                # lane): class-level inc/dec balance is the lexical
                # REFCOUNT-PAIR rule's beat, not a local lifecycle
                acq = None
            if acq is not None and self._raises_depth:
                # `with pytest.raises(...): pool.lease()` — the call is
                # asserted to raise, so nothing is ever acquired
                acq = None
            if acq is not None:
                kind, api = acq
                daemon = kind == "thread" and self._daemon_kw(call)
                self._open_record(None, kind, api, None, call, ctx,
                                  fin, daemon=daemon)
                for a in call.args:
                    self._expr(a, ctx, fin)
                for kw in call.keywords:
                    self._expr(kw.value, ctx, fin)
                return
        # the callee itself: a method ON a tracked handle / param
        if isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name
        ):
            name = func.value.id
            recs = self.open.get(name)
            if recs:
                self._op(name, func.attr, call, ctx, fin)
                if func.attr == "setDaemon":
                    for rec in recs:
                        rec["daemon"] = True
            else:
                entry = self._param_entry(name)
                if entry is not None and _res.release_api_any(func.attr):
                    entry["released"] = True
        elif isinstance(func, ast.Name):
            if self.open.get(func.id):
                self._op(func.id, "[called]", call, ctx, fin)
        else:
            self._expr(func, ctx, fin)
        # top-level arguments: handles/params handed to the callee
        recv_last = ""
        meth = None
        if isinstance(func, ast.Attribute):
            meth = func.attr
            recv_text = _expr_text(func.value)
            if recv_text:
                recv_last = _last_segment(recv_text)
        elif isinstance(func, ast.Name):
            meth = func.id
        callee = self._ref(call)
        nargs = len(call.args) + len(call.keywords)
        for i, arg in enumerate(call.args):
            argpos = i
            if isinstance(arg, ast.Starred):
                arg = arg.value
                argpos = -1
            if isinstance(arg, ast.Name):
                self._passed(arg.id, callee, nargs, argpos, meth,
                             recv_last, arg, ctx, fin)
            else:
                self._expr(arg, ctx, fin)
        for kw in call.keywords:
            if isinstance(kw.value, ast.Name):
                self._passed(kw.value.id, callee, nargs, -1, meth,
                             recv_last, kw.value, ctx, fin)
            else:
                self._expr(kw.value, ctx, fin)

    def _passed(self, name, callee, nargs, argpos, meth, recv_last,
                node, ctx, fin):
        recs = self.open.get(name)
        if recs:
            for rec in recs:
                rec["passed"].append({
                    "ref": callee, "nargs": nargs, "argpos": argpos,
                    "meth": meth, "recv": recv_last,
                    "line": node.lineno, "col": node.col_offset,
                    "ctx": list(ctx), "fin": fin,
                })
            return
        entry = self._param_entry(name)
        if entry is None:
            return
        if meth and _res.release_by_arg_any(meth, recv_last):
            entry["released"] = True
        elif callee is not None:
            entry["passed"].append([callee[0], callee[1], nargs, argpos])
        else:
            # handed to an unresolvable callee: claim ownership so the
            # CALLER treats its hand-off as a transfer (FN over FP)
            entry["passed"].append(["?", "", -1, -1])


def summarize_module(tree, path):
    """Build the ModuleSummary for one parsed file."""
    mod = ModuleSummary(path, module_name_for(path))
    mod.imports = _collect_imports(tree, mod.module)
    mod.jit_names = sorted(_jit_bound_names(tree))

    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            kind = _ctor_kind(node.value)
            if kind and kind != "semaphore":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        mod.module_locks[t.id] = kind

    # class inventory first: lock/sem/jit attrs inform the scanners
    def collect_class(cls):
        info = {"bases": [], "methods": [], "lock_attrs": {},
                "sem_attrs": [], "jit_attrs": [], "field_ctors": {}}
        for base in cls.bases:
            text = _expr_text(base)
            if text:
                info["bases"].append(text)
        for sub in ast.walk(cls):
            if isinstance(sub, ast.Assign) and isinstance(
                sub.value, ast.Call
            ):
                kind = _ctor_kind(sub.value)
                ttexts = [_expr_text(t) for t in sub.targets]
                for tt in ttexts:
                    if tt and tt.startswith("self."):
                        attr = tt[len("self."):]
                        if kind == "semaphore":
                            info["sem_attrs"].append(attr)
                        elif kind:
                            info["lock_attrs"][attr] = kind
                ftext = _expr_text(sub.value.func) or ""
                if ftext in ("jax.jit", "jit", "jax.pmap", "pmap"):
                    for tt in ttexts:
                        if tt and tt.startswith("self."):
                            info["jit_attrs"].append(tt[len("self."):])
                elif ftext and kind is None:
                    # which constructor each plain field came from — the
                    # lockset pass resolves these to spot fields holding
                    # instances of lock-owning (self-synchronized)
                    # classes
                    for tt in ttexts:
                        if tt and tt.startswith("self.") and (
                            "." not in tt[len("self."):]
                        ):
                            info["field_ctors"].setdefault(
                                tt[len("self."):], ftext
                            )
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info["methods"].append(item.name)
        mod.classes[cls.name] = info

    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            collect_class(node)

    def summarize_function(fn_node, cls_name, prefix, is_method):
        qual = f"{prefix}{fn_node.name}"
        args = fn_node.args
        pos = args.posonlyargs + args.args
        names = [a.arg for a in pos]
        skip_self = (
            1 if (is_method and names and names[0] in ("self", "cls"))
            else 0
        )
        n_pos = len(pos) - skip_self
        n_defaults = len(args.defaults)
        params_min = max(n_pos - n_defaults, 0)
        params_max = None if (args.vararg or args.kwarg) else (
            n_pos + len(args.kwonlyargs)
        )
        summary = FunctionSummary(
            qual, fn_node.name, cls_name, fn_node.lineno,
            fn_node.name.endswith("_locked"), params_min, params_max,
        )
        local_locks = {}
        for sub in ast.walk(fn_node):
            if isinstance(sub, ast.Assign) and isinstance(
                sub.value, ast.Call
            ):
                kind = _ctor_kind(sub.value)
                if kind and kind != "semaphore":
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            local_locks[t.id] = kind
        _FunctionScanner(mod, cls_name, summary, local_locks).scan(fn_node)
        _ResourceScanner(mod, summary).scan(fn_node)
        mod.functions[qual] = summary
        for child in _direct_nested(fn_node):
            # nested defs: own summary, class context inherited
            summarize_function(child, cls_name, f"{qual}.", False)

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.toplevel.append(node.name)
            summarize_function(node, None, "", False)
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    summarize_function(
                        item, node.name, f"{node.name}.", True
                    )
    return mod


# -- program assembly --------------------------------------------------------


class Program:
    """Resolved whole-program view over a set of ModuleSummaries."""

    def __init__(self, modules):
        self.modules = list(modules)
        self.by_module = {m.module: m for m in self.modules}
        # (module, qualname) -> (ModuleSummary, FunctionSummary)
        self.functions = {}
        # method name -> [(ModuleSummary, FunctionSummary)]
        self.methods_by_name = {}
        for m in self.modules:
            for qual, fn in m.functions.items():
                self.functions[(m.module, qual)] = (m, fn)
                if fn.cls is not None:
                    self.methods_by_name.setdefault(fn.name, []).append(
                        (m, fn)
                    )
        self._resolve_cache = {}

    def iter_functions(self):
        for m in self.modules:
            for fn in m.functions.values():
                yield m, fn

    # -- call resolution -----------------------------------------------------

    def _lookup_method(self, modsum, cls_name, method, _depth=0):
        """Find *method* on a class or its resolvable bases."""
        if _depth > 8 or modsum is None:
            return None
        info = modsum.classes.get(cls_name)
        if info is None:
            return None
        if method in info["methods"]:
            return self.functions.get(
                (modsum.module, f"{cls_name}.{method}")
            )
        for base in info["bases"]:
            base_mod, base_cls = self._resolve_class(modsum, base)
            if base_cls is not None:
                hit = self._lookup_method(
                    base_mod, base_cls, method, _depth + 1
                )
                if hit is not None:
                    return hit
        return None

    def _resolve_class(self, modsum, name):
        """(ModuleSummary, class name) for a class reference, if local or
        imported from an analyzed module."""
        if name in modsum.classes:
            return modsum, name
        target = modsum.imports.get(name.split(".", 1)[0])
        if target is None:
            return None, None
        if ":" in target:
            tmod, attr = target.split(":", 1)
            other = self.by_module.get(tmod)
            if other is not None and attr in other.classes:
                return other, attr
        else:
            other = self.by_module.get(target)
            if other is not None and "." in name:
                cls = name.split(".", 1)[1]
                if cls in other.classes:
                    return other, cls
        return None, None

    def _arity_ok(self, fn, nargs):
        if nargs < 0:
            return True
        if nargs < fn.params_min:
            return False
        return fn.params_max is None or nargs <= fn.params_max

    def resolve(self, modsum, caller, ref, nargs=-1):
        """Resolve a call reference to (ModuleSummary, FunctionSummary) or
        (None, None)."""
        key = (modsum.module, caller.qualname if caller else "", ref, nargs)
        if key in self._resolve_cache:
            return self._resolve_cache[key]
        result = self._resolve_uncached(modsum, caller, ref, nargs)
        self._resolve_cache[key] = result
        return result

    def _resolve_uncached(self, modsum, caller, ref, nargs):
        kind, value = ref
        if kind == "self":
            cls = caller.cls if caller else None
            if cls:
                hit = self._lookup_method(modsum, cls, value)
                if hit is not None:
                    return hit
            return None, None
        if kind == "name":
            if value in modsum.toplevel:
                return self.functions.get(
                    (modsum.module, value), (None, None)
                )
            if value in modsum.classes:
                return self._ctor(modsum, value)
            target = modsum.imports.get(value)
            if target is not None:
                return self._resolve_import_target(target)
            return None, None
        if kind == "dotted":
            base, rest = value.split(".", 1)
            if base in modsum.classes:
                # ClassName.method(...) — an unbound-call idiom
                hit = self._lookup_method(modsum, base, rest)
                return hit if hit is not None else (None, None)
            target = modsum.imports.get(base)
            if target is None:
                return None, None
            if ":" in target:
                tmod, attr = target.split(":", 1)
                other = self.by_module.get(tmod)
                if other is None:
                    return None, None
                return self._attr_in_module(other, f"{attr}.{rest}")
            other = self.by_module.get(target)
            if other is None:
                return None, None
            return self._attr_in_module(other, rest)
        if kind == "method":
            candidates = self.methods_by_name.get(value, ())
            live = [
                (m, f) for m, f in candidates if self._arity_ok(f, nargs)
            ]
            if len(live) == 1:
                return live[0]
            return None, None
        return None, None

    def _ctor(self, modsum, cls_name):
        hit = self._lookup_method(modsum, cls_name, "__init__")
        return hit if hit is not None else (None, None)

    def _attr_in_module(self, modsum, attr):
        if "." in attr:
            cls, method = attr.split(".", 1)
            if cls in modsum.classes:
                hit = self._lookup_method(modsum, cls, method)
                return hit if hit is not None else (None, None)
            return None, None
        if attr in modsum.toplevel:
            return self.functions.get((modsum.module, attr), (None, None))
        if attr in modsum.classes:
            return self._ctor(modsum, attr)
        return None, None

    def _resolve_import_target(self, target):
        if ":" in target:
            tmod, attr = target.split(":", 1)
            other = self.by_module.get(tmod)
            if other is None:
                return None, None
            return self._attr_in_module(other, attr)
        return None, None

    # -- convenience ---------------------------------------------------------

    def pseudo_required_lock(self, fn):
        """The pseudo lock id modeling the *_locked caller-holds-the-lock
        convention (never fed into the lock-order graph)."""
        owner = fn.cls or "<module>"
        return f"<caller-held:{owner}>"


def build_program(summaries):
    return Program(summaries)
