"""Dynamic lock-order + data-race witness: TSan-shaped evidence for the
static pass.

The static LOCK-INV / LOCKSET-RACE rules reason over *names*; this
module watches the *objects*.  Two witnesses share the held-lock
machinery:

- :class:`LockWitness` wraps the repo's lock/condition objects and
  records the actual acquisition DAG a test run exercises — every edge
  ``A -> B`` where some thread acquired B while holding A, stamped with
  the acquiring source sites — and reports any cycle.
- :class:`RaceWitness` (a LockWitness) additionally runs the Eraser
  lockset state machine *at runtime* on the shared fields of classes
  opted in via :func:`witness_shared` (or armed ad hoc with
  :meth:`RaceWitness.watch_class`): while installed, the class's
  ``__setattr__``/``__getattribute__`` are instrumented so every
  witnessed-field access consults the current thread's real held-lock
  stack.  A field starts first-thread-exclusive; once a second thread
  touches it, its *write lockset* is intersected write by write — an
  empty intersection (an unguarded or inconsistently-guarded write to a
  shared field) raises :class:`RaceViolation` carrying BOTH access
  stacks and dumps to the flight recorder when one is attached.
  Lock-free *reads* are tolerated by design: CPython reference loads
  are atomic, and the static pass's safe-publication exemption makes
  the same call — the witness checks the write-side protocol.

Static analysis and the witnesses keep each other honest: a race only
one side sees is either an unexercised static path (add a test) or a
dynamic aliasing pattern the summaries cannot name (add a rule).

Usage (tests)::

    w = LockWitness()
    with w.installed():           # patches threading.Lock/RLock/Condition
        run_concurrent_scenario() # locks built inside client_tpu/ record
    w.assert_acyclic()            # raises LockOrderViolation on a cycle

    w = RaceWitness()
    with w.installed():           # + instruments @witness_shared classes
        run_concurrent_scenario() # unguarded shared write -> raises
    w.assert_race_free()

The ``installed()`` patch only wraps locks *constructed from files under
the configured prefixes* (default ``client_tpu``): stdlib internals
(queue, threading.Event, logging) keep raw primitives, so overhead and
noise stay scoped to the code under test.  Lock identity is the
construction site (``client_tpu/balance/pool.py:223``) — all instances
born at one line share a name, which matches how the static pass (and a
human) reasons about lock order.

Pytest integration: ``--lock-witness`` / ``TPULINT_LOCK_WITNESS=1`` arms
a per-test LockWitness via the fixture in ``tests/conftest.py`` and
fails any test whose acquisition graph closed a cycle;
``TPULINT_RACE_WITNESS=1`` (the ``make chaos`` / ``make soak`` hookup)
arms a RaceWitness instead — lock-order duty included.

A third, independent witness covers the OWNERSHIP dimension:
:class:`ResourceWitness` (``--resource-witness`` /
``TPULINT_RESOURCE_WITNESS=1``) patches the registered acquire/release
pairs from ``analysis/resources.py``'s :data:`DYNAMIC_SPECS` (KV block
alloc/retain/release, endpoint leases, tracer spans) into a live-handle
table keyed per handle with the acquisition stack; a handle still live
at :meth:`ResourceWitness.assert_clean` — the per-test teardown audit
and a chaos-matrix invariant — raises :class:`ResourceLeakError` and
dumps the table plus stacks to the flight recorder.  The runtime
complement of the static RESOURCE-LEAK rule, from the same spec table.
"""

import contextlib
import os
import sys
import threading
import weakref

__all__ = [
    "LockOrderViolation",
    "LockWitness",
    "RaceViolation",
    "RaceWitness",
    "ResourceLeakError",
    "ResourceWitness",
    "WitnessLock",
    "WitnessCondition",
    "witness_shared",
]

_HERE = os.path.abspath(os.path.dirname(__file__))


class LockOrderViolation(AssertionError):
    """A cycle exists in the observed lock-acquisition graph."""


class RaceViolation(AssertionError):
    """A witnessed shared field was written with an empty lockset."""


def _call_site(prefixes):
    """The IMMEDIATE caller frame (first one outside this module) as
    ``relpath:lineno`` when it lives under one of *prefixes*; None
    otherwise.  Deliberately no deeper walk: a lock allocated by stdlib
    internals on behalf of client code (``Condition()``'s private RLock,
    ``queue.Queue``'s mutex) must stay a raw primitive — wrapping the
    RLock inside a Condition breaks its non-reentrant ``_is_owned``
    fallback probe."""
    frame = sys._getframe(2)
    while frame is not None:
        filename = frame.f_code.co_filename
        absfile = os.path.abspath(filename)
        if absfile.startswith(_HERE):
            frame = frame.f_back
            continue
        norm = absfile.replace(os.sep, "/")
        for prefix in prefixes:
            # a prefix names a PACKAGE, not a substring: a checkout
            # directory itself called client_tpu must not pull the whole
            # tree (tests included) into scope, so the matched component
            # has to be a real package root (it carries __init__.py)
            idx = 0
            needle = "/" + prefix + "/"
            while True:
                idx = norm.find(needle, idx)
                if idx < 0:
                    break
                if _is_package_dir(norm[: idx + 1 + len(prefix)]):
                    rel = norm[idx + 1:]
                    if not rel.startswith("client_tpu/analysis/"):
                        return f"{rel}:{frame.f_lineno}"
                idx += 1
        return None
    return None


_PKG_DIR_CACHE = {}


def _is_package_dir(d):
    hit = _PKG_DIR_CACHE.get(d)
    if hit is None:
        hit = os.path.isfile(os.path.join(d, "__init__.py"))
        _PKG_DIR_CACHE[d] = hit
    return hit


class LockWitness:
    """Collects the acquisition DAG; detects cycles as edges close them."""

    def __init__(self, prefixes=("client_tpu",)):
        self.prefixes = tuple(prefixes)
        self._mu = threading.Lock()
        # (held_name, acquired_name) -> {"held_site", "site", "count"}
        self._edges = {}
        self._tls = threading.local()
        self.violations = []  # [(cycle list, description)]
        self.acquisitions = 0

    # -- held-stack bookkeeping (per thread) --------------------------------

    def _stack(self):
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def note_acquire(self, name, site):
        stack = self._stack()
        with self._mu:
            self.acquisitions += 1
            for held_name, held_site in stack:
                if held_name == name:
                    continue  # re-entrant acquire: not an ordering edge
                edge = (held_name, name)
                entry = self._edges.get(edge)
                if entry is None:
                    self._edges[edge] = {
                        "held_site": held_site, "site": site, "count": 1,
                    }
                    # the new edge held->name closes a cycle iff a path
                    # name ~> held already existed
                    path = self._path_locked(name, held_name)
                    if path is not None:
                        cycle = [held_name] + path
                        self.violations.append(
                            (cycle, self._describe_locked(cycle))
                        )
                else:
                    entry["count"] += 1
        stack.append((name, site))

    def note_release(self, name):
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name:
                del stack[i]
                return

    # -- graph queries -------------------------------------------------------

    def _path_locked(self, src, dst):
        """A node path src..dst over current edges, else None."""
        adjacent = {}
        for a, b in self._edges:
            adjacent.setdefault(a, []).append(b)
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in adjacent.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _describe_locked(self, cycle):
        parts = []
        for i, a in enumerate(cycle):
            b = cycle[(i + 1) % len(cycle)]
            entry = self._edges.get((a, b))
            if entry is not None:
                parts.append(
                    f"{a} (held at {entry['held_site']}) -> "
                    f"{b} (acquired at {entry['site']})"
                )
        return " ; ".join(parts)

    def edges(self):
        """{(held, acquired): count} snapshot of the observed DAG."""
        with self._mu:
            return {e: d["count"] for e, d in self._edges.items()}

    def cycles(self):
        """Cycles recorded while the witness was armed."""
        with self._mu:
            return list(self.violations)

    def assert_acyclic(self):
        """Raise :class:`LockOrderViolation` if any acquisition cycle was
        observed; returns the edge count otherwise (so callers can assert
        the witness actually saw traffic)."""
        with self._mu:
            violations = list(self.violations)
            n_edges = len(self._edges)
        if violations:
            lines = [
                f"lock-order cycle: {' -> '.join(c + [c[0]])} ({how})"
                for c, how in violations
            ]
            raise LockOrderViolation(
                f"{len(violations)} lock-order cycle(s) observed:\n"
                + "\n".join(lines)
            )
        return n_edges

    # -- wrapping ------------------------------------------------------------

    def wrap_lock(self, lock, name):
        return WitnessLock(lock, name, self)

    def wrap_condition(self, cond, name):
        return WitnessCondition(cond, name, self)

    @contextlib.contextmanager
    def installed(self):
        """Patch ``threading.Lock/RLock/Condition`` so objects constructed
        from files under the witness prefixes are wrapped (everything else
        gets the raw primitive)."""
        real_lock = threading.Lock
        real_rlock = threading.RLock
        real_cond = threading.Condition
        witness = self

        def make_lock():
            site = _call_site(witness.prefixes)
            inner = real_lock()
            return (
                WitnessLock(inner, site, witness)
                if site is not None
                else inner
            )

        def make_rlock():
            site = _call_site(witness.prefixes)
            inner = real_rlock()
            return (
                WitnessLock(inner, site, witness)
                if site is not None
                else inner
            )

        def make_condition(lock=None):
            site = _call_site(witness.prefixes)
            if isinstance(lock, WitnessLock):
                # share the existing wrapper's identity; the condition
                # acquires through it
                inner = real_cond(lock._inner)
                return WitnessCondition(inner, lock._name, witness)
            inner = real_cond(lock)
            return (
                WitnessCondition(inner, site, witness)
                if site is not None
                else inner
            )

        threading.Lock = make_lock
        threading.RLock = make_rlock
        threading.Condition = make_condition
        try:
            yield self
        finally:
            threading.Lock = real_lock
            threading.RLock = real_rlock
            threading.Condition = real_cond


class WitnessLock:
    """Recording proxy over a Lock/RLock."""

    def __init__(self, inner, name, witness):
        self._inner = inner
        self._name = name
        self._w = witness

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._w.note_acquire(self._name, _call_site(self._w.prefixes))
        return ok

    def release(self):
        self._w.note_release(self._name)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def _is_owned(self):
        """Condition-compatibility: delegate RLock ownership, and answer
        the non-reentrant probe without re-recording (a wrapped lock
        handed to ``threading.Condition`` must keep its semantics)."""
        inner_owned = getattr(self._inner, "_is_owned", None)
        if inner_owned is not None:
            return inner_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"WitnessLock({self._name!r})"


class WitnessCondition:
    """Recording proxy over a Condition.

    ``wait``/``wait_for`` release the underlying lock for their duration:
    the witness pops the name while blocked and re-records the
    reacquisition (which IS an ordering event — waking up under other
    held locks is how wait-based inversions happen)."""

    def __init__(self, inner, name, witness):
        self._inner = inner
        self._name = name
        self._w = witness

    def acquire(self, *args, **kwargs):
        ok = self._inner.acquire(*args, **kwargs)
        if ok:
            self._w.note_acquire(self._name, _call_site(self._w.prefixes))
        return ok

    def release(self):
        self._w.note_release(self._name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout=None):
        self._w.note_release(self._name)
        try:
            return self._inner.wait(timeout)
        finally:
            self._w.note_acquire(
                self._name, _call_site(self._w.prefixes)
            )

    def wait_for(self, predicate, timeout=None):
        self._w.note_release(self._name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._w.note_acquire(
                self._name, _call_site(self._w.prefixes)
            )

    def notify(self, n=1):
        self._inner.notify(n)

    def notify_all(self):
        self._inner.notify_all()

    def __repr__(self):
        return f"WitnessCondition({self._name!r})"


# -- dynamic race witness ----------------------------------------------------

# Classes opted in via @witness_shared, with their declared guard/field
# spec.  The decorator records intent only — zero runtime cost until a
# RaceWitness is installed and patches the class.
_WITNESSED_CLASSES = []


def witness_shared(*guards, fields=None):
    """Class decorator opting a class into dynamic race witnessing.

    *guards* name the lock/condition attributes that protect the class's
    shared state (``@witness_shared("_lock")``) — they are excluded from
    witnessing themselves.  *fields* narrows witnessing to the named
    instance attributes; by default every instance attribute that is not
    a guard (and not class-level: methods, properties, class vars) is
    witnessed.  The decorator is free until a :class:`RaceWitness` is
    installed: no wrapper, no per-access cost, nothing changes on the
    class.
    """
    def decorate(cls):
        spec = {
            "guards": frozenset(guards),
            "fields": frozenset(fields) if fields is not None else None,
        }
        cls._tpulint_witness_shared = spec
        _WITNESSED_CLASSES.append(weakref.ref(cls))
        return cls
    return decorate


def _access_stack(limit=8):
    """Compact caller stack (outside this module), innermost first."""
    frame = sys._getframe(2)
    out = []
    while frame is not None and len(out) < limit:
        filename = frame.f_code.co_filename
        if not os.path.abspath(filename).startswith(_HERE):
            out.append(
                f"{filename}:{frame.f_lineno} in "
                f"{frame.f_code.co_name}"
            )
        frame = frame.f_back
    return out


class _FieldState:
    """Eraser state for one (instance, field)."""

    __slots__ = ("owner", "shared", "modified", "wlock", "last_write",
                 "last")

    def __init__(self, owner):
        self.owner = owner       # first-accessing thread ident
        self.shared = False      # a second thread has touched it
        self.modified = False    # written after becoming shared
        self.wlock = None        # candidate write lockset (None = all)
        self.last_write = None   # (thread, held, stack) of last write
        self.last = None         # same, for the last access of any kind


class RaceWitness(LockWitness):
    """LockWitness + runtime Eraser lockset checking on witnessed
    classes (see the module docstring for the algorithm and the
    read-side tolerance rationale).

    ``flight`` (optional) is a
    :class:`~client_tpu.serve.flight.FlightRecorder`: every violation is
    noted and the ring dumped, so a red chaos round ships the race
    evidence alongside its other postmortem artifacts.
    """

    def __init__(self, prefixes=("client_tpu",), flight=None):
        super().__init__(prefixes=prefixes)
        self.flight = flight
        self._race_mu = threading.Lock()
        self._obj_states = {}    # id(obj) -> {field: _FieldState}
        self._finalizers = {}    # id(obj) -> weakref.finalize
        self._extra_classes = []  # watch_class() registrations
        self.race_violations = []  # [(cls, field, description)]
        self.field_accesses = 0

    # -- opt-in surface ------------------------------------------------------

    def watch_class(self, cls, guards=(), fields=None):
        """Arm *cls* for this witness without the decorator — the
        programmatic hook for ad-hoc classes (seeded-race tests, classes
        named by a static LOCKSET-RACE verdict).  Call before
        ``installed()``."""
        self._extra_classes.append((cls, {
            "guards": frozenset(guards),
            "fields": frozenset(fields) if fields is not None else None,
        }))

    def _armed_classes(self):
        out = []
        seen = set()
        for ref in _WITNESSED_CLASSES:
            cls = ref()
            if cls is not None and id(cls) not in seen:
                seen.add(id(cls))
                out.append((cls, cls._tpulint_witness_shared))
        for cls, spec in self._extra_classes:
            # a decorated class passed to watch_class() again must not
            # be instrumented twice (double wrappers never fully unwind)
            if id(cls) not in seen:
                seen.add(id(cls))
                out.append((cls, spec))
        return out

    # -- the state machine ---------------------------------------------------

    def _held_names(self):
        return frozenset(name for name, _site in self._stack())

    def note_field_access(self, obj, cls, field, kind):
        """Run one access through the lockset state machine.  Raises
        :class:`RaceViolation` on an empty write lockset."""
        tid = threading.get_ident()
        held = self._held_names()
        info = (threading.current_thread().name, held, _access_stack())
        report = None
        with self._race_mu:
            self.field_accesses += 1
            key = id(obj)
            states = self._obj_states.get(key)
            if states is None:
                states = self._obj_states[key] = {}
                try:
                    self._finalizers[key] = weakref.finalize(
                        obj, self._drop_state, key
                    )
                except TypeError:
                    pass  # not weakref-able: entry lives with the witness
            state = states.get(field)
            if state is None:
                state = states[field] = _FieldState(tid)
            if tid != state.owner and not state.shared:
                state.shared = True  # first-thread-exclusive phase over
            if kind == "write":
                if state.shared:
                    state.modified = True
                    state.wlock = (
                        held if state.wlock is None
                        else state.wlock & held
                    )
                    if not state.wlock:
                        prior = state.last_write or state.last
                        report = self._describe_race(
                            cls, field, info, prior
                        )
                        self.race_violations.append(
                            (cls.__name__, field, report)
                        )
                state.last_write = info
            state.last = info
        if report is not None:
            self._dump_race(cls, field, report)
            raise RaceViolation(report)

    def _drop_state(self, key):
        # finalizers can fire inside any allocation — including while a
        # note_field_access holds _race_mu on this very thread — so a
        # blocking acquire here could self-deadlock.  Skipping the
        # cleanup just leaves an inert id-keyed entry behind.
        if self._race_mu.acquire(False):
            try:
                self._obj_states.pop(key, None)
                self._finalizers.pop(key, None)
            finally:
                self._race_mu.release()

    @staticmethod
    def _describe_race(cls, field, current, prior):
        def fmt(info):
            if info is None:
                return "  (no prior access recorded)"
            thread, held, stack = info
            locks = (
                "{" + ", ".join(sorted(held)) + "}" if held
                else "no locks"
            )
            frames = "\n".join(f"    {line}" for line in stack)
            return f"  thread {thread!r} holding {locks}:\n{frames}"

        return (
            f"unguarded shared write: {cls.__name__}.{field} written "
            "with an empty candidate lockset (no lock common to every "
            "write since the field became thread-shared)\n"
            "this access:\n" + fmt(current)
            + "\nprior conflicting access:\n" + fmt(prior)
        )

    def _dump_race(self, cls, field, report):
        flight = self.flight
        if flight is None:
            return
        try:
            flight.note(
                "race_witness_violation", cls=cls.__name__, field=field,
                report=report,
            )
            flight.dump(f"race-{cls.__name__}-{field}")
        except Exception:
            pass  # evidence is best-effort; the raise is the verdict

    def assert_race_free(self):
        """Raise :class:`RaceViolation` if any violation was recorded
        (covers violations swallowed by driver try/except); returns the
        witnessed access count otherwise."""
        with self._race_mu:
            violations = list(self.race_violations)
            n = self.field_accesses
        if violations:
            lines = [
                f"{cls}.{field}" for cls, field, _r in violations
            ]
            raise RaceViolation(
                f"{len(violations)} unguarded shared write(s) observed: "
                + ", ".join(lines) + "\n\n" + violations[0][2]
            )
        return n

    # -- class instrumentation ----------------------------------------------

    def _instrument(self, cls, spec):
        witness = self
        guards = spec["guards"]
        fields = spec["fields"]
        class_attrs = frozenset(dir(cls))
        had_set = "__setattr__" in cls.__dict__
        had_get = "__getattribute__" in cls.__dict__
        orig_set = cls.__setattr__
        orig_get = cls.__getattribute__

        def watched(name):
            if fields is not None:
                return name in fields
            return (
                not name.startswith("__")
                and name not in guards
                and name not in class_attrs
            )

        def instrumented_setattr(obj, name, value):
            if watched(name):
                witness.note_field_access(obj, cls, name, "write")
            orig_set(obj, name, value)

        def instrumented_getattribute(obj, name):
            value = orig_get(obj, name)
            if watched(name):
                witness.note_field_access(obj, cls, name, "read")
            return value

        cls.__setattr__ = instrumented_setattr
        cls.__getattribute__ = instrumented_getattribute
        return (cls, had_set, orig_set, had_get, orig_get)

    @contextlib.contextmanager
    def installed(self):
        """The LockWitness threading patch PLUS per-class
        ``__setattr__``/``__getattribute__`` instrumentation on every
        witnessed class, all restored on exit."""
        patched = []
        with super().installed():
            try:
                for cls, spec in self._armed_classes():
                    patched.append(self._instrument(cls, spec))
                yield self
            finally:
                # reversed: if a class ever carries stacked wrappers,
                # unwinding inner-first restores the true originals
                for cls, had_set, orig_set, had_get, orig_get in (
                    reversed(patched)
                ):
                    if had_set:
                        cls.__setattr__ = orig_set
                    else:
                        del cls.__setattr__
                    if had_get:
                        cls.__getattribute__ = orig_get
                    else:
                        del cls.__getattribute__


# -- dynamic resource-leak witness -------------------------------------------


class ResourceLeakError(AssertionError):
    """Handles acquired while the resource witness was armed are still
    live at a checkpoint."""


class ResourceWitness:
    """Live-handle table over the registered acquire/release pairs.

    The dynamic half of the resource-lifecycle analysis: while
    installed, every acquire/release pair in
    ``analysis/resources.py``'s :data:`~client_tpu.analysis.resources.
    DYNAMIC_SPECS` is patched so each acquisition registers the handle
    (with its acquisition stack) and each release retires it.  KV block
    references are counted per ``(pool, block)`` — a retain adds a
    reference the same release must drop — leases and spans are keyed by
    object identity.  A release of a handle acquired BEFORE the witness
    armed is ignored (the table audits the armed window, not history),
    so a function-scoped witness composes with session-scoped pools.

    :meth:`assert_clean` is the verdict: anything still live raises
    :class:`ResourceLeakError` carrying every leaked handle's kind,
    label, reference count, and acquisition stack, and — when a
    ``flight`` recorder is attached — dumps the table alongside the
    round's other postmortem artifacts.  Threads, sockets, and files
    stay static-only (see the DYNAMIC_SPECS comment): patching those
    class-wide would flag every stdlib-internal fd in the suite.
    """

    def __init__(self, flight=None):
        self.flight = flight
        self._mu = threading.Lock()
        self._live = {}  # key -> {"kind","label","count","stack"}
        self.acquisitions = 0
        self.releases = 0

    # -- the table -----------------------------------------------------------

    def _acquired(self, kind, key, label):
        stack = _access_stack()
        with self._mu:
            self.acquisitions += 1
            entry = self._live.get(key)
            if entry is None:
                self._live[key] = {
                    "kind": kind, "label": label, "count": 1,
                    "stack": stack,
                }
            else:
                entry["count"] += 1

    def _released(self, key):
        with self._mu:
            entry = self._live.get(key)
            if entry is None:
                return  # acquired before arming (or idempotent re-release)
            self.releases += 1
            entry["count"] -= 1
            if entry["count"] <= 0:
                del self._live[key]

    def live(self):
        """Snapshot of the live-handle table."""
        with self._mu:
            return {k: dict(v) for k, v in self._live.items()}

    def assert_clean(self):
        """Raise :class:`ResourceLeakError` when handles acquired while
        armed are still live; returns the acquisition count otherwise
        (so callers can assert the witness actually saw traffic)."""
        with self._mu:
            leaked = {k: dict(v) for k, v in self._live.items()}
            n = self.acquisitions
        if not leaked:
            return n
        lines = []
        for key, entry in sorted(
            leaked.items(), key=lambda kv: str(kv[0])
        ):
            frames = "\n".join(
                f"    {frame}" for frame in entry["stack"]
            )
            lines.append(
                f"  {entry['kind']} {entry['label']} "
                f"x{entry['count']} acquired at:\n{frames}"
            )
        report = (
            f"{len(leaked)} leaked resource handle(s) at witness "
            "checkpoint:\n" + "\n".join(lines)
        )
        self._dump_leak(leaked, report)
        raise ResourceLeakError(report)

    def _dump_leak(self, leaked, report):
        flight = self.flight
        if flight is None:
            return
        try:
            flight.note(
                "resource_witness_leak",
                handles=[
                    {"kind": e["kind"], "label": e["label"],
                     "count": e["count"], "stack": e["stack"]}
                    for e in leaked.values()
                ],
                report=report,
            )
            flight.dump("resource-leak")
        except Exception:
            pass  # evidence is best-effort; the raise is the verdict

    # -- patching ------------------------------------------------------------

    def _wrap_acquire(self, kind, cls, method, mode):
        orig = getattr(cls, method)
        witness = self

        def wrapped(self_obj, *args, **kwargs):
            out = orig(self_obj, *args, **kwargs)
            try:
                if mode == "ret-each":
                    for item in out or ():
                        witness._acquired(
                            kind, (kind, id(self_obj), item),
                            f"{cls.__name__}.{method}() block {item}",
                        )
                elif mode == "arg-each":
                    for item in (args[0] if args else ()) or ():
                        witness._acquired(
                            kind, (kind, id(self_obj), item),
                            f"{cls.__name__}.{method}() block {item}",
                        )
                elif mode == "ret" and out is not None:
                    witness._acquired(
                        kind, (kind, id(out)),
                        f"{cls.__name__}.{method}() -> "
                        f"{type(out).__name__}",
                    )
            except Exception:
                pass  # bookkeeping must never break the product call
            return out

        setattr(cls, method, wrapped)
        return cls, method, orig

    def _wrap_release(self, kind, cls, method, mode):
        orig = getattr(cls, method)
        witness = self

        def wrapped(self_obj, *args, **kwargs):
            out = orig(self_obj, *args, **kwargs)
            try:
                if mode == "arg-each":
                    for item in (args[0] if args else ()) or ():
                        witness._released((kind, id(self_obj), item))
                elif mode == "self":
                    witness._released((kind, id(self_obj)))
                elif mode == "arg" and args and args[0] is not None:
                    witness._released((kind, id(args[0])))
            except Exception:
                pass
            return out

        setattr(cls, method, wrapped)
        return cls, method, orig

    @contextlib.contextmanager
    def installed(self):
        """Patch every DYNAMIC_SPECS acquire/release pair (modules
        imported lazily — an absent optional surface is skipped), all
        restored on exit."""
        import importlib

        from client_tpu.analysis.resources import DYNAMIC_SPECS

        patched = []
        try:
            for spec in DYNAMIC_SPECS:
                try:
                    module = importlib.import_module(spec["module"])
                    cls = getattr(module, spec["cls"])
                except Exception:
                    continue
                for method, mode in spec["acquire"].items():
                    patched.append(
                        self._wrap_acquire(spec["kind"], cls, method,
                                           mode)
                    )
                for method, mode in spec["release"].items():
                    patched.append(
                        self._wrap_release(spec["kind"], cls, method,
                                           mode)
                    )
            yield self
        finally:
            # reversed: stacked witnesses unwind inner-first so the
            # true originals come back
            for cls, method, orig in reversed(patched):
                setattr(cls, method, orig)
