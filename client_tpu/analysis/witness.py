"""Dynamic lock-order witness: TSan-shaped evidence for the static pass.

The static LOCK-INV rule reasons over *names*; this module watches the
*objects*.  Opt-in wrappers around the repo's lock/condition objects
record the actual acquisition DAG a test run exercises — every edge
``A -> B`` where some thread acquired B while holding A, stamped with
the acquiring source sites — and report any cycle.  Static analysis and
the witness keep each other honest: a cycle only one of them sees is
either an unexercised static path (add a test) or a dynamic aliasing
pattern the summaries cannot name (add a rule).

Usage (tests)::

    w = LockWitness()
    with w.installed():           # patches threading.Lock/RLock/Condition
        run_concurrent_scenario() # locks built inside client_tpu/ record
    w.assert_acyclic()            # raises LockOrderViolation on a cycle

The ``installed()`` patch only wraps locks *constructed from files under
the configured prefixes* (default ``client_tpu``): stdlib internals
(queue, threading.Event, logging) keep raw primitives, so overhead and
noise stay scoped to the code under test.  Lock identity is the
construction site (``client_tpu/balance/pool.py:223``) — all instances
born at one line share a name, which matches how the static pass (and a
human) reasons about lock order.

Pytest integration: ``--lock-witness`` (or ``TPULINT_LOCK_WITNESS=1``,
the ``make soak`` hookup) arms a per-test witness via the fixture in
``tests/conftest.py`` and fails any test whose acquisition graph closed
a cycle.
"""

import contextlib
import os
import sys
import threading

__all__ = [
    "LockOrderViolation",
    "LockWitness",
    "WitnessLock",
    "WitnessCondition",
]

_HERE = os.path.abspath(os.path.dirname(__file__))


class LockOrderViolation(AssertionError):
    """A cycle exists in the observed lock-acquisition graph."""


def _call_site(prefixes):
    """The IMMEDIATE caller frame (first one outside this module) as
    ``relpath:lineno`` when it lives under one of *prefixes*; None
    otherwise.  Deliberately no deeper walk: a lock allocated by stdlib
    internals on behalf of client code (``Condition()``'s private RLock,
    ``queue.Queue``'s mutex) must stay a raw primitive — wrapping the
    RLock inside a Condition breaks its non-reentrant ``_is_owned``
    fallback probe."""
    frame = sys._getframe(2)
    while frame is not None:
        filename = frame.f_code.co_filename
        absfile = os.path.abspath(filename)
        if absfile.startswith(_HERE):
            frame = frame.f_back
            continue
        norm = absfile.replace(os.sep, "/")
        for prefix in prefixes:
            # a prefix names a PACKAGE, not a substring: a checkout
            # directory itself called client_tpu must not pull the whole
            # tree (tests included) into scope, so the matched component
            # has to be a real package root (it carries __init__.py)
            idx = 0
            needle = "/" + prefix + "/"
            while True:
                idx = norm.find(needle, idx)
                if idx < 0:
                    break
                if _is_package_dir(norm[: idx + 1 + len(prefix)]):
                    rel = norm[idx + 1:]
                    if not rel.startswith("client_tpu/analysis/"):
                        return f"{rel}:{frame.f_lineno}"
                idx += 1
        return None
    return None


_PKG_DIR_CACHE = {}


def _is_package_dir(d):
    hit = _PKG_DIR_CACHE.get(d)
    if hit is None:
        hit = os.path.isfile(os.path.join(d, "__init__.py"))
        _PKG_DIR_CACHE[d] = hit
    return hit


class LockWitness:
    """Collects the acquisition DAG; detects cycles as edges close them."""

    def __init__(self, prefixes=("client_tpu",)):
        self.prefixes = tuple(prefixes)
        self._mu = threading.Lock()
        # (held_name, acquired_name) -> {"held_site", "site", "count"}
        self._edges = {}
        self._tls = threading.local()
        self.violations = []  # [(cycle list, description)]
        self.acquisitions = 0

    # -- held-stack bookkeeping (per thread) --------------------------------

    def _stack(self):
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def note_acquire(self, name, site):
        stack = self._stack()
        with self._mu:
            self.acquisitions += 1
            for held_name, held_site in stack:
                if held_name == name:
                    continue  # re-entrant acquire: not an ordering edge
                edge = (held_name, name)
                entry = self._edges.get(edge)
                if entry is None:
                    self._edges[edge] = {
                        "held_site": held_site, "site": site, "count": 1,
                    }
                    # the new edge held->name closes a cycle iff a path
                    # name ~> held already existed
                    path = self._path_locked(name, held_name)
                    if path is not None:
                        cycle = [held_name] + path
                        self.violations.append(
                            (cycle, self._describe_locked(cycle))
                        )
                else:
                    entry["count"] += 1
        stack.append((name, site))

    def note_release(self, name):
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name:
                del stack[i]
                return

    # -- graph queries -------------------------------------------------------

    def _path_locked(self, src, dst):
        """A node path src..dst over current edges, else None."""
        adjacent = {}
        for a, b in self._edges:
            adjacent.setdefault(a, []).append(b)
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in adjacent.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def _describe_locked(self, cycle):
        parts = []
        for i, a in enumerate(cycle):
            b = cycle[(i + 1) % len(cycle)]
            entry = self._edges.get((a, b))
            if entry is not None:
                parts.append(
                    f"{a} (held at {entry['held_site']}) -> "
                    f"{b} (acquired at {entry['site']})"
                )
        return " ; ".join(parts)

    def edges(self):
        """{(held, acquired): count} snapshot of the observed DAG."""
        with self._mu:
            return {e: d["count"] for e, d in self._edges.items()}

    def cycles(self):
        """Cycles recorded while the witness was armed."""
        with self._mu:
            return list(self.violations)

    def assert_acyclic(self):
        """Raise :class:`LockOrderViolation` if any acquisition cycle was
        observed; returns the edge count otherwise (so callers can assert
        the witness actually saw traffic)."""
        with self._mu:
            violations = list(self.violations)
            n_edges = len(self._edges)
        if violations:
            lines = [
                f"lock-order cycle: {' -> '.join(c + [c[0]])} ({how})"
                for c, how in violations
            ]
            raise LockOrderViolation(
                f"{len(violations)} lock-order cycle(s) observed:\n"
                + "\n".join(lines)
            )
        return n_edges

    # -- wrapping ------------------------------------------------------------

    def wrap_lock(self, lock, name):
        return WitnessLock(lock, name, self)

    def wrap_condition(self, cond, name):
        return WitnessCondition(cond, name, self)

    @contextlib.contextmanager
    def installed(self):
        """Patch ``threading.Lock/RLock/Condition`` so objects constructed
        from files under the witness prefixes are wrapped (everything else
        gets the raw primitive)."""
        real_lock = threading.Lock
        real_rlock = threading.RLock
        real_cond = threading.Condition
        witness = self

        def make_lock():
            site = _call_site(witness.prefixes)
            inner = real_lock()
            return (
                WitnessLock(inner, site, witness)
                if site is not None
                else inner
            )

        def make_rlock():
            site = _call_site(witness.prefixes)
            inner = real_rlock()
            return (
                WitnessLock(inner, site, witness)
                if site is not None
                else inner
            )

        def make_condition(lock=None):
            site = _call_site(witness.prefixes)
            if isinstance(lock, WitnessLock):
                # share the existing wrapper's identity; the condition
                # acquires through it
                inner = real_cond(lock._inner)
                return WitnessCondition(inner, lock._name, witness)
            inner = real_cond(lock)
            return (
                WitnessCondition(inner, site, witness)
                if site is not None
                else inner
            )

        threading.Lock = make_lock
        threading.RLock = make_rlock
        threading.Condition = make_condition
        try:
            yield self
        finally:
            threading.Lock = real_lock
            threading.RLock = real_rlock
            threading.Condition = real_cond


class WitnessLock:
    """Recording proxy over a Lock/RLock."""

    def __init__(self, inner, name, witness):
        self._inner = inner
        self._name = name
        self._w = witness

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._w.note_acquire(self._name, _call_site(self._w.prefixes))
        return ok

    def release(self):
        self._w.note_release(self._name)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def _is_owned(self):
        """Condition-compatibility: delegate RLock ownership, and answer
        the non-reentrant probe without re-recording (a wrapped lock
        handed to ``threading.Condition`` must keep its semantics)."""
        inner_owned = getattr(self._inner, "_is_owned", None)
        if inner_owned is not None:
            return inner_owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"WitnessLock({self._name!r})"


class WitnessCondition:
    """Recording proxy over a Condition.

    ``wait``/``wait_for`` release the underlying lock for their duration:
    the witness pops the name while blocked and re-records the
    reacquisition (which IS an ordering event — waking up under other
    held locks is how wait-based inversions happen)."""

    def __init__(self, inner, name, witness):
        self._inner = inner
        self._name = name
        self._w = witness

    def acquire(self, *args, **kwargs):
        ok = self._inner.acquire(*args, **kwargs)
        if ok:
            self._w.note_acquire(self._name, _call_site(self._w.prefixes))
        return ok

    def release(self):
        self._w.note_release(self._name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout=None):
        self._w.note_release(self._name)
        try:
            return self._inner.wait(timeout)
        finally:
            self._w.note_acquire(
                self._name, _call_site(self._w.prefixes)
            )

    def wait_for(self, predicate, timeout=None):
        self._w.note_release(self._name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._w.note_acquire(
                self._name, _call_site(self._w.prefixes)
            )

    def notify(self, n=1):
        self._inner.notify(n)

    def notify_all(self):
        self._inner.notify_all()

    def __repr__(self):
        return f"WitnessCondition({self._name!r})"
