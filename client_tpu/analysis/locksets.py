"""Eraser-style whole-program lockset inference (the LOCKSET-RACE core).

The lexical SHARED-MUT rule sees one file and one shape: an unlocked
*assignment* outside the thread closure.  It cannot see a field written
under ``self._lock`` in one method and read lock-free from a background
thread three calls away, or writes guarded by one lock racing reads
guarded by a *different* one.  This module runs the classic lockset
algorithm (Eraser, Savage et al. 1997; ThreadSanitizer's hybrid follows
the same idea) statically over the :mod:`callgraph` summaries:

1. **Escape analysis** — a class is *threaded* when any of its methods
   (or their nested defs) is registered as a deferred callable
   (``threading.Thread(target=self._loop)``, a registered callback edge).
   Only threaded classes are analyzed: a class nobody hands to a thread
   has no second thread root to race with.
2. **Thread roots** — one root per deferred target, plus ``<main>``
   covering the class's public surface (non-underscore methods; the
   calling thread's side of every race this repo shipped).  Private
   helpers are attributed to whichever roots actually reach them through
   same-instance (``self.``) calls and local nested defs.
3. **Interprocedural held sets** — each root is walked with the held-lock
   set carried across call edges (lexical ``with`` sets from the
   summaries, unioned down the chain; a ``*_locked`` callee adds its
   ``<caller-held:Class>`` pseudo lock).  Every shared-field access is
   stamped with the full lexical+interprocedural set and the root chain
   that reached it.
4. **Lockset verdicts** — per field, Eraser-style: the candidate guard
   set is the intersection of held sets across accesses.  A field is a
   race when a *write* and another access from a *different* root have
   disjoint locksets.  Exemptions (documented FN > noisy FP):

   - ``__init__`` is never walked: constructor writes are the virgin /
     first-thread-exclusive phase (no second thread can exist yet for
     the fields it initializes);
   - fields only touched from one root are single-threaded;
   - fields with no write outside ``__init__`` are effectively frozen;
   - event/queue/thread-named fields hold internally synchronized (or
     handle-only) objects — flagging ``self._stop.set()`` would drown
     the gate;
   - a ``<caller-held:Class>`` pseudo lock intersects everything: the
     ``*_locked`` convention vouches for the caller;
   - a field assigned an instance of a *lock-owning analyzed class*
     (``self.seq_store = _SequenceStore(...)``) is self-synchronized:
     the delegate's own lock is its discipline (checked by its own
     analysis and, for ``@witness_shared`` classes, the dynamic
     witness); deeper paths that reach around it stay checked;
   - **safe publication**: a field whose every write is a pure
     reference rebind (``self.x = v``, never ``self.x[k] = v`` or
     ``self.x.append(...)``) under one consistent guard may be read
     lock-free — the GIL makes reference loads atomic, so readers see
     the old or the new object, never a torn one (the
     ``set_registry``/``fleet.attach`` post-fix shape).  The pre-fix
     shape (unguarded rebind) still has an empty write-lockset
     intersection and is flagged.

Each verdict carries *both* witness sites (file:line, the holding set at
each, and the thread-root chain that reached it) so the finding reads as
a race report, not a style nit.  The dynamic twin of this pass is
:class:`client_tpu.analysis.witness.RaceWitness`, which runs the same
state machine against the real held-lock stack at runtime.
"""

from client_tpu.analysis.callgraph import (
    _EVENTISH_RE,
    _QUEUEISH_RE,
    _THREADISH_RE,
)

_MAX_DEPTH = 10       # call-chain depth per root walk
_MAX_STATES = 4000    # (function, entry-held) states per class walk

MAIN_ROOT = "<main>"


def _is_synced_field(attr):
    """Fields whose names mark internally synchronized/handle objects
    (events, queues, thread handles) — their methods are the sanctioned
    cross-thread API, not racy data accesses.  *attr* is an access path
    (``kv.pools``): any synced segment exempts the path."""
    return any(
        _EVENTISH_RE.search(seg)
        or _QUEUEISH_RE.search(seg)
        or _THREADISH_RE.search(seg)
        for seg in attr.split(".")
    )


def _is_pseudo(lock):
    return lock.startswith("<caller-held:")


class Access:
    """One shared-field access, fully attributed."""

    __slots__ = ("attr", "kind", "deep", "path", "line", "col", "held",
                 "root", "chain")

    def __init__(self, attr, kind, deep, path, line, col, held, root,
                 chain):
        self.attr = attr
        self.kind = kind          # "read" | "write"
        self.deep = deep          # write mutates the field's object
        self.path = path
        self.line = line
        self.col = col
        self.held = held          # frozenset of lock ids
        self.root = root          # root name (qualname or <main>)
        self.chain = chain        # tuple of qualnames from the root

    def site(self):
        locks = (
            "{" + ", ".join(sorted(self.held)) + "}"
            if self.held else "no locks"
        )
        return (
            f"{self.path}:{self.line} ({self.kind} holding {locks}, "
            f"via {self.root}: {' -> '.join(self.chain)})"
        )


class RaceReport:
    """One field whose candidate lockset went empty across ≥2 roots."""

    __slots__ = ("cls", "attr", "write", "other", "roots")

    def __init__(self, cls, attr, write, other, roots):
        self.cls = cls
        self.attr = attr
        self.write = write    # the witness write Access
        self.other = other    # the second witness Access (another root)
        self.roots = roots    # all roots that touch the field

    def message(self):
        return (
            f"field {self.cls}.{self.attr} has an empty candidate "
            f"lockset across thread roots "
            f"({', '.join(sorted(self.roots))}): "
            f"written at {self.write.site()} racing "
            f"{self.other.kind} at {self.other.site()} — guard every "
            "access with one consistent lock (or confine the field to "
            "one thread)"
        )


def _nested_lookup(mod, caller, name):
    """A nested def (``Cls.method.loop``) referenced by bare name."""
    return mod.functions.get(f"{caller.qualname}.{name}")


def _deferred_targets(program, mod, cls_name):
    """(roots, spawners) for the class: ``roots`` maps each deferred
    callable's qualname (Thread targets and registered callbacks
    resolving to the class's own methods or their nested defs) to its
    (mod, fn); ``spawners`` is the set of qualnames of the methods that
    *register* them — their writes precede the thread's start in every
    shape this repo uses (``start()`` spawns last), so they share
    ``__init__``'s virgin-phase exemption."""
    roots = {}
    spawners = set()
    for fn in mod.functions.values():
        if fn.cls != cls_name:
            continue
        for call in fn.calls:
            if not call["deferred"]:
                continue
            kind, value = call["ref"]
            target = None
            if kind == "self":
                tmod, tfn = program.resolve(mod, fn, ("self", value))
                if tfn is not None:
                    target = (tmod, tfn)
            elif kind == "name":
                tfn = _nested_lookup(mod, fn, value)
                if tfn is not None:
                    target = (mod, tfn)
            if target is not None:
                roots[target[1].qualname] = target
                spawners.add(fn.qualname)
    return roots, spawners


def _self_synced_fields(program, mod, cls_name):
    """Fields assigned an instance of a lock-owning analyzed class
    (``self.seq_store = _SequenceStore(...)`` where ``_SequenceStore``
    constructs its own ``_lock``): the object synchronizes itself, so
    method calls through the field are the sanctioned pattern — its
    internal discipline is checked by its own class's analysis (and,
    for ``@witness_shared`` classes, by the dynamic witness)."""
    info = mod.classes.get(cls_name, {})
    synced = set()
    for attr, ctor in info.get("field_ctors", {}).items():
        cmod, ccls = program._resolve_class(mod, ctor)
        if ccls is None:
            continue
        if cmod.classes.get(ccls, {}).get("lock_attrs"):
            synced.add(attr)
    return synced


def _main_entries(program, mod, cls_name, deferred):
    """The class's public surface: externally callable methods that are
    not thread roots themselves (the calling thread's side)."""
    info = mod.classes.get(cls_name, {})
    entries = []
    for method in info.get("methods", []):
        if method == "__init__" or method.startswith("__"):
            continue
        if method.startswith("_"):
            continue
        qual = f"{cls_name}.{method}"
        if qual in deferred:
            continue
        hit = mod.functions.get(qual)
        if hit is not None:
            entries.append((mod, hit))
    return entries


def _walk_root(program, mod, cls_name, root_name, entries, exempt=()):
    """Collect every shared-field access reachable from *entries*, each
    stamped with the lexically+interprocedurally held lock set.  Direct
    accesses in ``__init__`` and in *exempt* (spawn) methods are the
    virgin phase and are skipped; their callees still count."""
    accesses = []
    seen = set()
    stack = []
    for emod, efn in entries:
        held = frozenset(
            [program.pseudo_required_lock(efn)]
            if efn.requires_lock else []
        )
        stack.append((emod, efn, held, (efn.qualname,)))
    while stack:
        m, fn, held, chain = stack.pop()
        key = (m.module, fn.qualname, held)
        if key in seen or len(seen) > _MAX_STATES:
            continue
        seen.add(key)
        for acc in fn.accesses:
            if fn.name == "__init__" or fn.qualname in exempt:
                continue
            eff = held | frozenset(acc["held"])
            accesses.append(Access(
                acc["attr"], acc["kind"], acc.get("deep", False),
                m.path, acc["line"], acc["col"], eff, root_name, chain,
            ))
        if len(chain) >= _MAX_DEPTH:
            continue
        for call in fn.calls:
            if call["deferred"]:
                continue
            kind, value = call["ref"]
            if kind == "self":
                cmod, cfn = program.resolve(
                    m, fn, call["ref"], call["nargs"]
                )
            elif kind == "name":
                cfn = _nested_lookup(m, fn, value)
                cmod = m if cfn is not None else None
            else:
                continue  # other instances' methods are their own class
            if cfn is None or cfn.name == "__init__":
                continue
            sub_held = held | frozenset(call["held"])
            if cfn.requires_lock:
                sub_held = sub_held | {
                    program.pseudo_required_lock(cfn)
                }
            stack.append((cmod, cfn, sub_held, chain + (cfn.qualname,)))
    return accesses


def _disjoint(a, b):
    """Locksets share nothing — and neither carries the *_locked pseudo
    lock (the caller-holds-the-lock convention vouches for the site)."""
    if a & b:
        return False
    if any(_is_pseudo(lock) for lock in a | b):
        return False
    return True


def analyze(program):
    """Run the lockset pass; returns a list of :class:`RaceReport`."""
    reports = []
    for mod in program.modules:
        for cls_name in sorted(mod.classes):
            deferred, spawners = _deferred_targets(program, mod, cls_name)
            if not deferred:
                continue  # instances never escape to another thread
            per_root = {}
            for root_name, target in sorted(deferred.items()):
                per_root[root_name] = _walk_root(
                    program, mod, cls_name, root_name, [target],
                    exempt=spawners,
                )
            mains = _main_entries(program, mod, cls_name, deferred)
            if mains:
                per_root[MAIN_ROOT] = _walk_root(
                    program, mod, cls_name, MAIN_ROOT, mains,
                    exempt=spawners,
                )
            synced = _self_synced_fields(program, mod, cls_name)
            reports.extend(_verdicts(cls_name, per_root, synced))
    return reports


def _verdicts(cls_name, per_root, self_synced=frozenset()):
    by_attr = {}
    for root_name, accesses in per_root.items():
        for acc in accesses:
            if _is_synced_field(acc.attr):
                continue
            if acc.attr in self_synced:
                # the field's object owns its own lock (see
                # _self_synced_fields); deeper paths that reach AROUND
                # that lock (self.store._entries[...]) stay checked
                continue
            by_attr.setdefault(acc.attr, []).append(acc)
    reports = []
    for attr in sorted(by_attr):
        records = by_attr[attr]
        roots = {acc.root for acc in records}
        if len(roots) < 2:
            continue  # single-threaded field
        writes = sorted(
            (a for a in records if a.kind == "write"),
            key=lambda a: (a.path, a.line, a.col),
        )
        if not writes:
            continue  # frozen after __init__: reads cannot race
        if all(not w.deep for w in writes):
            # safe publication: every write is a pure reference rebind
            # and all rebinds share a guard — readers see either the old
            # or the new reference atomically (GIL), never a torn state.
            # Interior mutation (deep writes) never qualifies.
            common = writes[0].held
            for w in writes[1:]:
                common = common & w.held
            if common:
                continue
        others = sorted(
            records,
            key=lambda a: (a.kind != "write", a.path, a.line, a.col),
        )
        witness = None
        for w in writes:
            for other in others:
                if other.root == w.root:
                    continue
                if not _disjoint(w.held, other.held):
                    continue
                if other.kind != "write" and w.deep and not other.deep:
                    # an interior mutation races interior observers
                    # (subscripts, iteration, method calls) — a bare
                    # reference load stays GIL-atomic regardless
                    continue
                witness = (w, other)
                break
            if witness:
                break
        if witness is None:
            continue  # every cross-root pair shares a guard
        reports.append(RaceReport(
            cls_name, attr, witness[0], witness[1], roots,
        ))
    return reports
