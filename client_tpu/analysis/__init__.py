"""tpu-lint: AST-based concurrency & array-semantics analyzer.

Encodes this repo's recurring bug shapes as enforced rules — numpy
truthiness in control flow, blocking calls in async bodies, device
dispatch under scheduler locks, streaming queues abandoned without their
close sentinel, loop-less ``Condition.wait``, unlocked writes to
thread-shared state — plus three whole-program rules over a project-wide
call graph with per-function lock summaries: lock-order inversion
(LOCK-INV), blocking work reached under a lock through any call depth
(BLOCK-UNDER-LOCK), and observer callbacks invoked while a private lock
is held (CALLBACK-UNDER-LOCK).  A dynamic lock-order witness
(``client_tpu.analysis.witness``) records the real acquisition DAG under
test and keeps the static pass honest.

Run ``python -m client_tpu.analysis [paths]`` (exits non-zero on
findings) or ``make lint``.

Pure stdlib on purpose: the gate must run anywhere the repo checks out,
with or without jax present.
"""

from client_tpu.analysis.core import (  # noqa: F401
    Finding,
    PROGRAM_REGISTRY,
    ProgramRule,
    REGISTRY,
    Rule,
    all_rules,
    scan_paths,
    scan_source,
)
from client_tpu.analysis import rules as _rules  # noqa: F401  (registers)
from client_tpu.analysis import (  # noqa: F401  (registers)
    concurrency as _concurrency,
)

__all__ = [
    "Finding",
    "PROGRAM_REGISTRY",
    "ProgramRule",
    "REGISTRY",
    "Rule",
    "all_rules",
    "scan_paths",
    "scan_source",
]
