"""tpu-lint: AST-based concurrency & array-semantics analyzer.

Encodes this repo's recurring bug shapes as enforced rules — numpy
truthiness in control flow, blocking calls in async bodies, device
dispatch under scheduler locks, streaming queues abandoned without their
close sentinel, loop-less ``Condition.wait``, and unlocked writes to
thread-shared state.  Run ``python -m client_tpu.analysis [paths]``
(exits non-zero on findings) or ``make lint``.

Pure stdlib on purpose: the gate must run anywhere the repo checks out,
with or without jax present.
"""

from client_tpu.analysis.core import (  # noqa: F401
    Finding,
    REGISTRY,
    Rule,
    scan_paths,
    scan_source,
)
from client_tpu.analysis import rules as _rules  # noqa: F401  (registers)

__all__ = ["Finding", "REGISTRY", "Rule", "scan_paths", "scan_source"]
