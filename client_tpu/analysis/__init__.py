"""tpu-lint: AST-based concurrency & array-semantics analyzer.

Encodes this repo's recurring bug shapes as enforced rules — numpy
truthiness in control flow, blocking calls in async bodies, device
dispatch under scheduler locks, streaming queues abandoned without their
close sentinel, loop-less ``Condition.wait``, unlocked writes to
thread-shared state, waivers that outlived their hazard
(STALE-SUPPRESS) — plus whole-program rules over a project-wide call
graph with per-function lock summaries: lock-order inversion
(LOCK-INV), blocking work reached under a lock through any call depth
(BLOCK-UNDER-LOCK), observer callbacks invoked while a private lock is
held (CALLBACK-UNDER-LOCK), peer RPCs under engine/pool locks
(PEER-CALL-UNDER-LOCK), Eraser-style per-field lockset inference
across thread roots (LOCKSET-RACE, ``analysis/locksets.py``), and
interprocedural resource-lifecycle ownership tracking (RESOURCE-LEAK,
DOUBLE-RELEASE, USE-AFTER-RELEASE, ``analysis/resources.py``).  Dynamic
witnesses (``client_tpu.analysis.witness``) keep the static pass
honest: ``LockWitness`` records the real acquisition DAG under test,
``RaceWitness`` runs the lockset algorithm at runtime on
``@witness_shared`` classes (``TPULINT_RACE_WITNESS=1``), and
``ResourceWitness`` keeps a live-handle table over the registered
acquire/release pairs (``TPULINT_RESOURCE_WITNESS=1``).

Run ``python -m client_tpu.analysis [paths]`` (exits non-zero on
findings) or ``make lint``.

Pure stdlib on purpose: the gate must run anywhere the repo checks out,
with or without jax present.
"""

__all__ = [
    "Finding",
    "PROGRAM_REGISTRY",
    "ProgramRule",
    "REGISTRY",
    "Rule",
    "all_rules",
    "scan_paths",
    "scan_source",
]


def _load_core():
    """Import the analyzer on first use (PEP 562 lazy init).

    Production modules import ``client_tpu.analysis.witness`` for the
    ``@witness_shared`` decorator — a stdlib-only leaf.  An eager
    package init would drag the full rule catalog (rules, callgraph,
    concurrency, locksets) into every serving/perf process just to
    attach an inert class attribute; loading lazily keeps the product
    free of the lint tool until someone actually lints."""
    from client_tpu.analysis import core
    from client_tpu.analysis import resources  # noqa: F401  (registers)
    from client_tpu.analysis import rules  # noqa: F401  (registers)
    from client_tpu.analysis import (  # noqa: F401  (registers)
        concurrency,
    )
    return core


def __getattr__(name):
    if name in __all__:
        return getattr(_load_core(), name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )
