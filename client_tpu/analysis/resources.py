"""Interprocedural resource-lifecycle analysis: one spec table, three
whole-program rules, and the acquire/release vocabulary shared with the
dynamic :class:`~client_tpu.analysis.witness.ResourceWitness`.

The reference client's hardest bug class is handle lifecycle — shared
memory regions, registered handles, connections that must be released on
every path — and this repo reproduces it in Python form: refcounted KV
blocks, endpoint leases, tracer spans, threads, sockets, files.  The
lexical rules (REFCOUNT-PAIR, SPAN-LEAK) each froze one syntactic shape;
this module is the engine behind them: a registered *spec table* names
every acquire/release pair in the repo, ``callgraph.py`` records
*resource events* into each function summary (acquisition sites, release
sites, ownership transfers: returned / yielded / stored to an attribute
/ passed to a callee whose summary takes ownership), and three rules
walk must-release over the whole program:

- **RESOURCE-LEAK** — an acquired handle can go out of scope unreleased
  and untransferred: never released at all, released only on some
  branches, or leaked on an explicit early ``return``/``raise`` path.
  ``with`` acquisition and a release inside a ``finally`` are the
  recognized exception-safe shapes.  This is the interprocedural
  generalization of SPAN-LEAK/REFCOUNT-PAIR: a handle acquired through a
  *wrapper* (``blocks = self._reserve(n)`` where ``_reserve`` returns a
  fresh ``kv.alloc``) is tracked through the callee's summary, which no
  per-file pass can see.
- **DOUBLE-RELEASE** — two release sites reachable on one path with no
  re-acquisition between them.  For a refcounted handle the second
  release decrements someone else's reference (the block is freed out
  from under its other holder); only kinds whose release is NOT
  idempotent participate (``Lease.release`` guards on ``_done``,
  ``Thread.join`` re-joins — those are exempt by spec).
- **USE-AFTER-RELEASE** — a method call / subscript / iteration on a
  handle reachable after its release on the same path: splicing freed
  block indices into a lane table, reading a closed file.

Precision choices (documented FN > noisy FP, same contract as the
concurrency pass):

- a handle that escapes — returned, yielded, stored to an attribute or
  container, or passed to ANY call we cannot resolve — transfers
  ownership and is exempt; only a resolved callee whose summary provably
  does not take ownership keeps the handle with the caller;
- path sensitivity is branch-arm bookkeeping, not a real CFG: two events
  are "on one path" only when their ``if``/``try`` arms agree (plus the
  try-body/finally and except/finally pairings), so an either-or release
  pair is never called a double release;
- an early-exit leak is only reported for an *explicit* ``return`` or
  ``raise`` between acquire and release — implicit exception edges are
  covered by requiring nothing; a release inside any ``finally`` (or a
  ``with`` acquisition) marks the handle exception-safe and ends the
  walk;
- ``if handle is None: return`` guards (the KV admission-backpressure
  idiom) are recognized: the exit on the None arm never leaks a handle
  that was never acquired.

The same table drives the dynamic half: :data:`DYNAMIC_SPECS` lists the
live classes whose acquire/release methods the ResourceWitness patches
under ``TPULINT_RESOURCE_WITNESS=1``.
"""

import re

from client_tpu.analysis.core import Finding, ProgramRule, register_program

__all__ = [
    "SPECS",
    "DYNAMIC_SPECS",
    "classify_acquire",
    "release_api",
    "release_api_any",
    "release_by_arg_any",
    "acquire_by_arg",
    "ResourceLeakRule",
    "DoubleReleaseRule",
    "UseAfterReleaseRule",
]

# -- the spec table ----------------------------------------------------------
#
# Shared vocabulary: rules.py's lexical SPAN-LEAK/REFCOUNT-PAIR
# pre-filters, callgraph.py's resource-event scanner, and the dynamic
# ResourceWitness all read these — one registration per acquire/release
# pair in the repo, everywhere.

_TRACERISH_RE = re.compile(r"(?i)tracer")
# start_tick: the continuous profiler's tick handles (serve/prof.py)
# follow the same bracket discipline as trace spans — an unfinished
# tick is a hole in the attribution timeline.
_SPAN_START_METHODS = {"start_span", "begin_span", "start_timer",
                       "start_tick"}
_SPAN_FINISH_METHODS = {"complete", "finish", "close", "end", "stop"}
_REFCOUNT_NAME_RE = re.compile(
    r"(^|_)(refs?|ref_?counts?)$", re.IGNORECASE
)
# receivers whose alloc/retain/release traffic is KV block-pool traffic
_KV_POOLISH_RE = re.compile(r"(?i)(^|_)(kv|pools?|block_?pool)s?$")
# receivers whose lease() hands out an endpoint lease
_LEASE_POOLISH_RE = re.compile(r"(?i)(^|_)(pools?|endpoints?|balancer)s?$")


class ResourceSpec:
    """One registered acquire/release pair family."""

    __slots__ = ("kind", "noun", "acquire_methods", "recv_re",
                 "release_methods", "release_by_arg", "ctors",
                 "idempotent_release", "why")

    def __init__(self, kind, noun, acquire_methods=(), recv_re=None,
                 release_methods=(), release_by_arg=(), ctors=(),
                 idempotent_release=False, why=""):
        self.kind = kind
        self.noun = noun
        self.acquire_methods = frozenset(acquire_methods)
        self.recv_re = recv_re           # receiver gate for method acquires
        self.release_methods = frozenset(release_methods)
        # methods releasing the handle PASSED AS AN ARGUMENT
        # (kv.release(blocks), tracer.complete(trace))
        self.release_by_arg = frozenset(release_by_arg)
        self.ctors = frozenset(ctors)    # constructor callee texts
        self.idempotent_release = idempotent_release
        self.why = why                   # one-line leak consequence


SPECS = {
    "kv-blocks": ResourceSpec(
        "kv-blocks", "KV block reservation",
        acquire_methods={"alloc", "retain"}, recv_re=_KV_POOLISH_RE,
        release_by_arg={"release", "free"},
        why=("a leaked reference is a block the pool can neither free "
             "nor read — the pool shrinks until admission bricks"),
    ),
    "lease": ResourceSpec(
        "lease", "endpoint lease",
        acquire_methods={"lease"}, recv_re=_LEASE_POOLISH_RE,
        release_methods={"release", "success", "failure"},
        idempotent_release=True,  # Lease methods guard on _done
        why=("an unreleased lease pins the endpoint's inflight count — "
             "the balancer routes around a replica that is actually "
             "idle"),
    ),
    "span": ResourceSpec(
        "span", "trace span",
        acquire_methods=_SPAN_START_METHODS | {"sample"},
        recv_re=None,  # sample() additionally gated on a tracer-ish recv
        release_methods=_SPAN_FINISH_METHODS,
        release_by_arg={"complete", "finish"},
        why=("an unfinished span vanishes from the trace file and the "
             "flight recorder exactly when the timeline matters"),
    ),
    "thread": ResourceSpec(
        "thread", "thread",
        ctors={"threading.Thread", "Thread"},
        release_methods={"join", "stop"},
        idempotent_release=True,
        why=("a non-daemon thread never joined outlives its owner and "
             "blocks interpreter shutdown"),
    ),
    "socket": ResourceSpec(
        "socket", "socket",
        ctors={"socket.socket", "socket.create_connection"},
        release_methods={"close", "shutdown", "detach"},
        idempotent_release=True,
        why=("an unclosed socket leaks the fd and holds the peer's "
             "accept slot until the GC gets around to it"),
    ),
    "file": ResourceSpec(
        "file", "file handle",
        ctors={"open", "io.open"},
        release_methods={"close"},
        idempotent_release=True,
        why="an unclosed file leaks the fd and may lose buffered writes",
    ),
}

# The live classes the dynamic ResourceWitness patches
# (TPULINT_RESOURCE_WITNESS=1).  Modes: how the handle rides the call —
#   ret       the return value is the handle (None = not acquired)
#   ret-each  the return value is a list of handles (each tracked)
#   arg-each  the first positional argument is a list of handles
#   arg       the first positional argument is the handle
#   self      the receiver is the handle
# Threads/sockets/files stay static-only: patching them class-wide would
# flag every fire-and-forget daemon and stdlib-internal fd in the suite.
DYNAMIC_SPECS = (
    {"kind": "kv-blocks", "module": "client_tpu.serve.lm.kv",
     "cls": "KvBlockPool",
     "acquire": {"alloc": "ret-each", "retain": "arg-each"},
     "release": {"release": "arg-each"}},
    {"kind": "lease", "module": "client_tpu.balance.pool",
     "cls": "EndpointPool", "acquire": {"lease": "ret"}, "release": {}},
    {"kind": "lease", "module": "client_tpu.balance.pool", "cls": "Lease",
     "acquire": {},
     "release": {"release": "self", "success": "self", "failure": "self"}},
    {"kind": "span", "module": "client_tpu.tracing", "cls": "ClientTracer",
     "acquire": {"sample": "ret"}, "release": {"complete": "arg"}},
    {"kind": "span", "module": "client_tpu.serve.tracing", "cls": "Tracer",
     "acquire": {"sample": "ret"}, "release": {"complete": "arg"}},
)


def _split_callee(text):
    """(receiver-last-segment, method) for a dotted callee text."""
    if "." not in text:
        return "", text
    recv, method = text.rsplit(".", 1)
    return recv.rsplit(".", 1)[-1], method


def classify_acquire(text):
    """(kind, api) when calling *text* acquires a registered resource,
    else None.  *text* is the dotted callee (``self.kv.alloc``,
    ``open``, ``threading.Thread``)."""
    if not text:
        return None
    recv_last, method = _split_callee(text)
    for spec in SPECS.values():
        if text in spec.ctors or (
            spec.kind == "thread" and method == "Thread"
        ):
            return spec.kind, method
    if method in ("alloc", "retain") and _KV_POOLISH_RE.search(recv_last):
        return "kv-blocks", method
    if method == "lease" and _LEASE_POOLISH_RE.search(recv_last):
        return "lease", method
    if method in _SPAN_START_METHODS:
        return "span", method
    if method == "sample" and _TRACERISH_RE.search(recv_last):
        return "span", method
    return None


def release_api(kind, method, recv_last="", by_arg=False):
    """True when *method* releases a handle of *kind* — called ON the
    handle (``by_arg=False``) or with the handle as an argument
    (``by_arg=True``, receiver-gated like the acquire side)."""
    spec = SPECS.get(kind)
    if spec is None:
        return False
    if by_arg:
        if method not in spec.release_by_arg:
            return False
        if kind == "kv-blocks":
            return bool(_KV_POOLISH_RE.search(recv_last))
        if kind == "span":
            return bool(_TRACERISH_RE.search(recv_last))
        return True
    return method in spec.release_methods


_ALL_RELEASE_METHODS = frozenset().union(
    *(spec.release_methods for spec in SPECS.values())
)


def release_api_any(method):
    """*method* called ON a handle releases SOME registered kind — the
    kind-agnostic test the scanner applies to parameters (whose kind is
    only known interprocedurally)."""
    return method in _ALL_RELEASE_METHODS


def release_by_arg_any(method, recv_last=""):
    """*method* releases a handle passed as an argument for some kind
    (receiver-gated the same way the acquire side is)."""
    return any(
        release_api(kind, method, recv_last, by_arg=True)
        for kind in SPECS
    )


def acquire_by_arg(kind, method, recv_last):
    """Calling ``pool.method(handle)`` ADDS a reference to the handle —
    a `retain` between two releases makes the second one legitimate
    (each reference gets its own release)."""
    if kind == "kv-blocks":
        return method == "retain" and bool(
            _KV_POOLISH_RE.search(recv_last or "")
        )
    return False


def _split_events(record, kind):
    """(releases, uses, passes) for one handle record.

    Ops and argument-passes are recorded kind-agnostically at scan time
    (a candidate wrapper-call record cannot know its kind until the
    callee's summary is resolved); once *kind* is known, method calls in
    the spec's release set become releases, everything else an op is a
    use, and a pass whose callee releases-by-argument (``kv.release(
    blocks)``) is a release rather than an ownership-transfer candidate.
    """
    releases, uses, passes = [], [], []
    for op in record["ops"]:
        api = op["api"]
        if not api.startswith("[") and release_api(kind, api):
            releases.append(op)
        elif not api.startswith("[attr "):
            # plain attribute reads are metadata (lease.key after
            # failure(), thread.name after join()) — never a
            # use-after-release; subscripts, iteration, calls are
            uses.append(op)
    for p in record["passed"]:
        meth = p.get("meth")
        if meth and release_api(kind, meth, p.get("recv", ""),
                                by_arg=True):
            releases.append(dict(p, api=meth))
        else:
            passes.append(p)
    return releases, uses, passes


# -- path-context algebra ----------------------------------------------------
#
# Contexts are lists of "nid:arm" tokens pushed by the callgraph scanner
# for every enclosing if/try/loop arm — branch-arm bookkeeping, not a
# CFG.  nid is "<kind><line>"; arms: t/e (if then/else), b/h{i}/o/f (try
# body/i-th handler/orelse/final), l (loop body).

# arms a release may add relative to the acquire and still run on the
# fall-through path (loop bodies may run zero times: excluded)
_FALLTHROUGH_ARMS = {"b", "o", "f"}


def _arm_conditional(arm):
    """The arm only runs on some paths through its node (if arms,
    exception handlers)."""
    return arm in ("t", "e") or arm.startswith("h")


def _arm_seq(a1, a2):
    """Two DIFFERENT arms at one try node that still lie on one
    sequential path: body→orelse→finally run in order, and any handler
    pairs with that try's finally (both run on the exception path).
    Distinct handlers — and if/else arms — are exclusive."""
    pair = {a1, a2}
    if pair <= _FALLTHROUGH_ARMS:
        return True
    if "f" in pair:
        other = (pair - {"f"}).pop()
        return other.startswith("h") or other in _FALLTHROUGH_ARMS
    return False


def _ctx_map(ctx):
    out = {}
    for token in ctx:
        nid, arm = token.rsplit(":", 1)
        out[nid] = arm
    return out


def _same_path(c1, c2):
    """Both events provably lie on one sequential path: every shared
    branch node agrees (or is a sequential try pairing), and neither
    event sits in a conditional arm the other is outside of."""
    m1, m2 = _ctx_map(c1), _ctx_map(c2)
    for nid in set(m1) | set(m2):
        a1, a2 = m1.get(nid), m2.get(nid)
        if a1 is None or a2 is None:
            if _arm_conditional(a1 or a2):
                return False
            continue
        if a1 != a2 and not _arm_seq(a1, a2):
            return False
    return True


def _reachable_from(acq_ctx, ctx):
    """The event at *ctx* is reachable on SOME path from the acquisition
    at *acq_ctx*: shared branch nodes must agree (conditional arms the
    event adds are fine — that is what makes it a path)."""
    ma, mc = _ctx_map(acq_ctx), _ctx_map(ctx)
    for nid, arm in ma.items():
        other = mc.get(nid)
        if other is not None and other != arm and not _arm_seq(
            arm, other
        ):
            return False
    return True


def _unconditional_after(acq_ctx, rel_ctx):
    """The release at *rel_ctx* runs on the fall-through continuation of
    the acquisition at *acq_ctx* (no new conditional arm, no new loop)."""
    ma, mr = _ctx_map(acq_ctx), _ctx_map(rel_ctx)
    for nid, arm in mr.items():
        if nid in ma:
            if ma[nid] != arm and not _arm_seq(ma[nid], arm):
                return False
            continue
        if arm not in _FALLTHROUGH_ARMS:
            return False
    return True


# -- interprocedural ownership flows -----------------------------------------

_MAX_DEPTH = 10


class _Flows:
    """Memoized transitive ownership queries over function summaries."""

    def __init__(self, program):
        self.program = program
        self._returns = {}
        self._owns = {}

    def returns_kind(self, mod, fn, _depth=0):
        """The resource kind *fn* returns freshly acquired, or None —
        following direct ``return pool.alloc(n)`` shapes and chains of
        ``return self._reserve(n)`` through resolvable callees."""
        key = (mod.module, fn.qualname)
        if key in self._returns:
            return self._returns[key]
        if _depth > _MAX_DEPTH:
            return None
        self._returns[key] = None  # cycle guard
        facts = fn.res_facts or {}
        kind = facts.get("returns")
        if kind is None:
            for ref_kind, ref_value, nargs in facts.get("ret_calls", ()):
                cmod, cfn = self.program.resolve(
                    mod, fn, (ref_kind, ref_value), nargs
                )
                if cfn is None or cfn is fn:
                    continue
                kind = self.returns_kind(cmod, cfn, _depth + 1)
                if kind is not None:
                    break
        self._returns[key] = kind
        return kind

    def owns_param(self, mod, fn, idx, _depth=0):
        """*fn* takes ownership of positional parameter *idx*: releases
        it, stores it, or hands it to a callee that does."""
        key = (mod.module, fn.qualname, idx)
        if key in self._owns:
            return self._owns[key]
        if _depth > _MAX_DEPTH:
            return False
        self._owns[key] = False  # cycle guard
        facts = fn.res_facts or {}
        entry = None
        for info in facts.get("params", {}).values():
            if info["idx"] == idx:
                entry = info
                break
        owned = False
        if entry is not None:
            if entry["released"] or entry["stored"]:
                owned = True
            else:
                for ref_kind, ref_value, nargs, argpos in entry["passed"]:
                    if argpos < 0:
                        owned = True  # kw pass: benefit of the doubt
                        break
                    cmod, cfn = self.program.resolve(
                        mod, fn, (ref_kind, ref_value), nargs
                    )
                    if cfn is None:
                        owned = True  # unresolvable: benefit of the doubt
                        break
                    if self.owns_param(cmod, cfn, argpos, _depth + 1):
                        owned = True
                        break
        self._owns[key] = owned
        return owned


def _record_kind(flows, program, mod, fn, record):
    """Resolve one handle record's resource kind (direct or through the
    wrapper call it was bound from), or None when it is not a resource."""
    if record["res"] is not None:
        return record["res"]
    via = record.get("via")
    if not via:
        return None
    cmod, cfn = program.resolve(mod, fn, (via[0], via[1]), via[2])
    if cfn is None:
        return None
    return flows.returns_kind(cmod, cfn)


def _transferred(flows, program, mod, fn, passes, record):
    """Ownership left the function: returned/yielded/stored, or passed
    to a callee that takes it (unresolvable callees get the benefit of
    the doubt — documented FN over noisy FP).  *passes* is the
    NON-release subset of the record's argument-passes — handing a
    handle to ``kv.release()`` is a release, not a transfer."""
    if record["escapes"]:
        return True
    for passed in passes:
        ref = passed["ref"]
        if ref is None or passed["argpos"] < 0:
            return True
        cmod, cfn = program.resolve(
            mod, fn, (ref[0], ref[1]), passed["nargs"]
        )
        if cfn is None:
            return True
        if flows.owns_param(cmod, cfn, passed["argpos"]):
            return True
    return False


def _iter_resource_records(program, flows):
    """Yield (mod, fn, record, kind, (releases, uses, passes)) for every
    resolvable handle record in the program."""
    for mod, fn in program.iter_functions():
        for record in fn.resources or ():
            kind = _record_kind(flows, program, mod, fn, record)
            if kind is None:
                continue
            yield mod, fn, record, kind, _split_events(record, kind)


def _handle_desc(record, kind):
    noun = SPECS[kind].noun
    var = record["var"]
    if var is None:
        return f"{noun} from {record['api']}()"
    return f"{noun} {var!r} (from {record['api']}())"


@register_program
class ResourceLeakRule(ProgramRule):
    """RESOURCE-LEAK — an acquired handle can go out of scope unreleased
    and untransferred.

    Every resource in the spec table (KV block reservations, endpoint
    leases, tracer spans, threads, sockets, files) must be released on
    EVERY path out of its owning function, or ownership must leave the
    function: returned/yielded to the caller, stored on an attribute, or
    passed to a callee whose summary takes it.  ``with`` acquisition and
    a release inside a ``finally`` are the exception-safe shapes; a
    release that only happens on some branches, or an explicit
    ``return``/``raise`` that exits between acquire and release, leaks
    the handle exactly when an error path runs — which is how every leak
    in this repo actually shipped (the pool shrinks, the balancer pins an
    idle replica, the trace file gets a hole).

    This is the interprocedural generalization of SPAN-LEAK and
    REFCOUNT-PAIR: a handle acquired through a WRAPPER (``blocks =
    self._reserve(n)`` where ``_reserve`` returns a fresh ``alloc``) is
    tracked through the callee's summary — invisible to any per-file
    pass.  Direct single-function span leaks stay with the lexical
    SPAN-LEAK pre-filter (one finding per bug).
    """

    id = "RESOURCE-LEAK"
    rationale = (
        "a handle not released on every path (and not transferred) "
        "leaks exactly when an error path runs — the KV pool shrinks "
        "until admission bricks, the lease pins an idle replica, the "
        "span vanishes from the timeline"
    )

    def check_program(self, program):
        flows = _Flows(program)
        findings = []
        for mod, fn, record, kind, events in _iter_resource_records(
            program, flows
        ):
            if record["in_with"]:
                continue
            if kind == "span" and record["res"] == "span":
                # direct, single-function span brackets are the lexical
                # SPAN-LEAK rule's beat; the engine owns wrapper-acquired
                # spans (record["res"] is None, kind resolved here)
                continue
            if kind == "thread" and record.get("daemon"):
                continue  # fire-and-forget daemon: dies with the process
            releases, _uses, passes = events
            if _transferred(flows, program, mod, fn, passes, record):
                continue
            spec = SPECS[kind]
            desc = _handle_desc(record, kind)
            if not releases:
                findings.append(Finding(
                    self.id, mod.path, record["line"], record["col"],
                    f"{fn.qualname}() acquires {desc} and never "
                    f"releases or transfers it — {spec.why}", "",
                ))
                continue
            covered = [
                r for r in releases
                if _unconditional_after(record["ctx"], r["ctx"])
            ]
            if not covered:
                first = min(releases, key=lambda r: r["line"])
                findings.append(Finding(
                    self.id, mod.path, record["line"], record["col"],
                    f"{fn.qualname}() releases {desc} only on some "
                    f"paths (the release at line {first['line']} sits "
                    f"in a conditional branch) — {spec.why}", "",
                ))
                continue
            if any(r["fin"] for r in releases):
                continue  # finally-protected: exception edges covered
            leak_exit = self._leaking_exit(fn, record, releases, covered)
            if leak_exit is not None:
                findings.append(Finding(
                    self.id, mod.path, record["line"], record["col"],
                    f"{fn.qualname}() leaks {desc} on the "
                    f"{leak_exit['kind']} path at line "
                    f"{leak_exit['line']} — the release at line "
                    f"{covered[0]['line']} is never reached there; "
                    "move it into a finally (or use a context "
                    f"manager) — {spec.why}", "",
                ))
        return findings

    @staticmethod
    def _leaking_exit(fn, record, releases, covered):
        """An explicit return/raise between acquire and the covering
        release with no release before it on its path, or None."""
        first_cover = min(r["line"] for r in covered)
        var = record["var"]
        exits = (fn.res_facts or {}).get("exits", ())
        for ex in exits:
            if not record["line"] < ex["line"] < first_cover:
                continue
            if var is not None and var in ex.get("guards", ()):
                continue  # `if handle is None: return` — nothing held
            if not _reachable_from(record["ctx"], ex["ctx"]):
                continue
            if any(
                r["line"] < ex["line"]
                and _same_path(r["ctx"], ex["ctx"])
                for r in releases
            ):
                continue
            return ex
        return None


@register_program
class DoubleReleaseRule(ProgramRule):
    """DOUBLE-RELEASE — two release sites reachable on one path with no
    re-acquisition between them.

    For a refcounted handle the second release decrements SOMEONE
    ELSE'S reference: the KV pool frees a block another request still
    maps, and the next alloc hands the same block to two owners — the
    corruption surfaces far from the bug.  Only kinds whose release is
    not idempotent participate (``Lease``'s methods guard on ``_done``,
    ``Thread.join``/``file.close`` re-run safely — exempt by spec);
    either-or branches (``if``/``else``, ``except`` vs the no-raise
    path) are never paired, but a release in an ``except`` arm plus one
    in the SAME try's ``finally`` does fire — both run on the exception
    path.
    """

    id = "DOUBLE-RELEASE"
    rationale = (
        "a second release on one path drops someone else's reference — "
        "the pool frees a block another holder still maps and the next "
        "alloc double-books it"
    )

    def check_program(self, program):
        flows = _Flows(program)
        findings = []
        for mod, fn, record, kind, events in _iter_resource_records(
            program, flows
        ):
            if SPECS[kind].idempotent_release:
                continue
            releases = sorted(events[0], key=lambda r: r["line"])
            reacqs = [
                p for p in events[2]
                if p.get("meth") and acquire_by_arg(
                    kind, p["meth"], p.get("recv", "")
                )
            ]
            for i, first in enumerate(releases):
                hit = None
                for second in releases[i + 1:]:
                    if second["line"] == first["line"]:
                        continue
                    if not _same_path(first["ctx"], second["ctx"]):
                        continue
                    if any(
                        p["line"] < second["line"]
                        and _same_path(p["ctx"], second["ctx"])
                        for p in reacqs
                    ):
                        # a retain before the second release added a
                        # reference of its own — the pair is the normal
                        # share-then-drain shape (FN over FP: one
                        # retain waives all later pairs on the path)
                        continue
                    hit = second
                    break
                if hit is None:
                    continue
                desc = _handle_desc(record, kind)
                findings.append(Finding(
                    self.id, mod.path, hit["line"], hit["col"],
                    f"{fn.qualname}() releases {desc} twice on one "
                    f"path ({first['api']}() at line {first['line']}, "
                    f"then {hit['api']}() at line {hit['line']} with "
                    "no re-acquisition between) — the second release "
                    "drops someone else's reference", "",
                ))
                break  # one finding per handle
        return findings


@register_program
class UseAfterReleaseRule(ProgramRule):
    """USE-AFTER-RELEASE — a handle operation reachable after its
    release on the same path.

    Released block indices spliced into a lane table scatter new KV
    writes into blocks the free list has already handed to another
    request; a closed file read raises at best.  The rule pairs each
    release with any later method call, subscript, iteration, or
    argument-pass of the same handle whose branch arms lie on the same
    sequential path; either-or branches are exempt (releasing in one arm
    and using in the other is the normal hand-off shape).
    """

    id = "USE-AFTER-RELEASE"
    rationale = (
        "touching a handle after its release operates on storage the "
        "pool already handed to another owner — corruption that "
        "surfaces far from the bug"
    )

    def check_program(self, program):
        flows = _Flows(program)
        findings = []
        for mod, fn, record, kind, events in _iter_resource_records(
            program, flows
        ):
            if kind == "thread":
                # a joined Thread object stays fully valid — is_alive()
                # after join() is the canonical did-it-finish check,
                # nothing about the handle is freed
                continue
            releases, op_uses, passes = events
            if not releases:
                continue
            uses = list(op_uses) + [
                dict(p, api="passed to " + (
                    str(p["ref"][1]) if p["ref"] else "a call"
                ) + "()")
                for p in passes
            ]
            hit = None
            for use in sorted(uses, key=lambda u: u["line"]):
                for rel in releases:
                    if use["line"] <= rel["line"]:
                        continue
                    if rel["fin"] and not use.get("fin"):
                        continue  # finally releases run last
                    if _same_path(rel["ctx"], use["ctx"]):
                        hit = (rel, use)
                        break
                if hit:
                    break
            if hit is None:
                continue
            rel, use = hit
            desc = _handle_desc(record, kind)
            findings.append(Finding(
                self.id, mod.path, use["line"], use.get("col", 0),
                f"{fn.qualname}() uses {desc} at line {use['line']} "
                f"({use['api']}) after releasing it at line "
                f"{rel['line']} — the handle may already belong to "
                "another owner", "",
            ))
        return findings
