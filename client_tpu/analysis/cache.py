"""Incremental-analysis cache for tpu-lint.

Parsing + summarizing every file dominates a `make lint` run; almost no
file changes between runs.  The cache persists, per file, the post-
suppression per-file findings, the serialized
:class:`~client_tpu.analysis.callgraph.ModuleSummary` (program rules
re-run every time — they are cheap graph walks over the summaries), and
the suppression map, keyed on ``(path, mtime, size)`` and guarded by a
**rules hash** over the analyzer's own sources: editing any rule
invalidates everything (a stale cache must never green-light a finding a
new rule would catch).

The cache file lives next to the analyzer (gitignored).  Corruption,
version skew, or a rules-hash mismatch silently degrade to a full scan —
the cache is an accelerator, never a correctness dependency.
``--no-cache`` on the CLI is the escape hatch.
"""

import hashlib
import json
import os

_VERSION = 3  # v3: summaries carry resource events (resources/res_facts)
DEFAULT_CACHE = os.path.join(os.path.dirname(__file__), ".cache.json")


def rules_hash():
    """Content hash over every analyzer source file (rule edits, driver
    edits, and callgraph changes all invalidate the cache)."""
    here = os.path.dirname(__file__)
    h = hashlib.sha256()
    for name in sorted(os.listdir(here)):
        if not name.endswith(".py"):
            continue
        h.update(name.encode("utf-8"))
        with open(os.path.join(here, name), "rb") as fh:
            h.update(fh.read())
        h.update(b"\x00")
    return h.hexdigest()


class AnalysisCache:
    """mtime-keyed per-file result cache (see module docstring).

    Beyond per-file entries, one **program entry** caches the whole-
    program pass (program rules + the STALE-SUPPRESS audit) keyed on a
    digest over every scanned file's ``(path, stat-key)``: edit one file
    and only that file re-analyzes but the program pass reruns; touch
    nothing and both come straight from cache.
    """

    def __init__(self, path=DEFAULT_CACHE):
        self.path = path
        self._rules_hash = rules_hash()
        self._entries = {}
        self._program = None  # {"digest": ..., "findings": [...]}
        self._dirty = False
        self.hits = 0
        self.misses = 0
        self.program_hits = 0
        self.program_misses = 0
        self._load()

    def _load(self):
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            return
        if (
            data.get("version") != _VERSION
            or data.get("rules_hash") != self._rules_hash
        ):
            return  # analyzer changed: every cached result is suspect
        entries = data.get("files")
        if isinstance(entries, dict):
            self._entries = entries
        program = data.get("program")
        if isinstance(program, dict) and "digest" in program:
            self._program = program

    def stat_key(self, path):
        """Freshness key for *path* (None when unstattable).  Callers
        storing results MUST capture this BEFORE reading the file: a save
        landing between the read and the store must make the entry look
        stale (re-scan), never fresh (silently serving findings for
        content nobody analyzed)."""
        try:
            st = os.stat(path)
        except OSError:
            return None
        return [int(st.st_mtime_ns), int(st.st_size)]

    def get(self, path):
        """Cached analysis for *path* if its stat key still matches."""
        entry = self._entries.get(path)
        key = self.stat_key(path)
        if entry is None or key is None or entry.get("stat") != key:
            self.misses += 1
            return None
        self.hits += 1
        return entry["data"]

    def stat_for(self, path):
        """The stat key stored with *path*'s entry (None when absent) —
        the fileset digest reuses it so a cache hit never re-stats."""
        entry = self._entries.get(path)
        return entry.get("stat") if entry else None

    def put(self, path, data, key):
        """Store *data* under the stat *key* captured before the read."""
        if key is None:
            return
        self._entries[path] = {"stat": key, "data": data}
        self._dirty = True

    def fileset_digest(self, fileset):
        """Digest over the full scanned fileset's (path, stat-key)
        pairs — the whole-program pass's freshness key.  Order-free:
        the same files in any scan order digest identically."""
        h = hashlib.sha256()
        for path, key in sorted(fileset):
            h.update(path.encode("utf-8"))
            h.update(repr(key).encode("utf-8"))
            h.update(b"\x00")
        return h.hexdigest()

    def get_program(self, digest):
        """Cached whole-program findings (as dicts) when the fileset
        digest still matches, else None."""
        entry = self._program
        if entry is None or entry.get("digest") != digest:
            self.program_misses += 1
            return None
        self.program_hits += 1
        return entry["findings"]

    def put_program(self, digest, findings):
        self._program = {"digest": digest, "findings": findings}
        self._dirty = True

    def save(self):
        if not self._dirty:
            return
        payload = {
            "version": _VERSION,
            "rules_hash": self._rules_hash,
            "files": self._entries,
            "program": self._program,
        }
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(payload, fh)
            os.replace(tmp, self.path)
        except OSError:
            # a read-only checkout still lints; it just lints cold
            try:
                os.unlink(tmp)
            except OSError:
                pass
        self._dirty = False
