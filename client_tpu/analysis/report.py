"""tpu-lint reporters: human text and machine JSON."""

import json


def render_text(new, grandfathered, rules):
    """Return the human report as a string (one finding per line)."""
    lines = []
    for f in new:
        lines.append(f.render())
    if new:
        lines.append("")
    counts = {}
    for f in new:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    summary = ", ".join(f"{r}:{n}" for r, n in sorted(counts.items()))
    lines.append(
        f"tpu-lint: {len(new)} finding(s)"
        + (f" [{summary}]" if summary else "")
        + (
            f", {len(grandfathered)} grandfathered (baseline)"
            if grandfathered
            else ""
        )
    )
    return "\n".join(lines)


def render_json(new, grandfathered, rules):
    return json.dumps(
        {
            "findings": [f.to_dict() for f in new],
            "grandfathered": [f.to_dict() for f in grandfathered],
            "rules": {
                rule.id: rule.rationale for rule in rules.values()
            },
            "count": len(new),
        },
        indent=2,
    )


def render_sarif(new, grandfathered, rules):
    """SARIF 2.1.0 — the interchange format CI annotators, editors, and
    code-scanning UIs consume directly.  Grandfathered (baselined)
    findings are emitted with ``"baselineState": "unchanged"`` so a
    consumer can show or hide the ratchet debt; new findings are
    ``level: error`` (they fail the gate)."""
    def result(f, baselined):
        out = {
            "ruleId": f.rule,
            "level": "note" if baselined else "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace("\\", "/"),
                    },
                    "region": {
                        "startLine": max(int(f.line), 1),
                        "startColumn": max(int(f.col) + 1, 1),
                    },
                },
            }],
        }
        if baselined:
            out["baselineState"] = "unchanged"
        return out

    driver = {
        "name": "tpu-lint",
        "informationUri": (
            "https://github.com/tpu-client/tpu-client"
            "#static-analysis"
        ),
        "rules": [
            {
                "id": rule.id,
                "shortDescription": {"text": rule.rationale},
                "fullDescription": {
                    "text": (type(rule).__doc__ or "").strip(),
                },
            }
            for _rid, rule in sorted(rules.items())
        ],
    }
    return json.dumps(
        {
            "$schema": (
                "https://raw.githubusercontent.com/oasis-tcs/"
                "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"
            ),
            "version": "2.1.0",
            "runs": [{
                "tool": {"driver": driver},
                "results": (
                    [result(f, False) for f in new]
                    + [result(f, True) for f in grandfathered]
                ),
            }],
        },
        indent=2,
    )


def render_rules(rules):
    lines = ["tpu-lint rule catalog:"]
    for rule_id in sorted(rules):
        lines.append(f"  {rule_id:20s} {rules[rule_id].rationale}")
    lines.append(
        "suppress in place with `# tpulint: disable=RULE -- why` (same "
        "line or a comment line above); reason-less suppressions are "
        "BARE-SUPPRESS findings"
    )
    return "\n".join(lines)


def render_explain(rules, rule_id):
    """Full rationale for one rule (class docstring + one-liner), or
    None when the id is unknown."""
    rule = rules.get(rule_id.strip().upper())
    if rule is None:
        return None
    doc = (type(rule).__doc__ or "").strip("\n")
    lines = [f"{rule.id}: {rule.rationale}", ""]
    if doc:
        lines.append(doc)
    return "\n".join(lines)
