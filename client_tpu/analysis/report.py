"""tpu-lint reporters: human text and machine JSON."""

import json


def render_text(new, grandfathered, rules):
    """Return the human report as a string (one finding per line)."""
    lines = []
    for f in new:
        lines.append(f.render())
    if new:
        lines.append("")
    counts = {}
    for f in new:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    summary = ", ".join(f"{r}:{n}" for r, n in sorted(counts.items()))
    lines.append(
        f"tpu-lint: {len(new)} finding(s)"
        + (f" [{summary}]" if summary else "")
        + (
            f", {len(grandfathered)} grandfathered (baseline)"
            if grandfathered
            else ""
        )
    )
    return "\n".join(lines)


def render_json(new, grandfathered, rules):
    return json.dumps(
        {
            "findings": [f.to_dict() for f in new],
            "grandfathered": [f.to_dict() for f in grandfathered],
            "rules": {
                rule.id: rule.rationale for rule in rules.values()
            },
            "count": len(new),
        },
        indent=2,
    )


def render_rules(rules):
    lines = ["tpu-lint rule catalog:"]
    for rule_id in sorted(rules):
        lines.append(f"  {rule_id:20s} {rules[rule_id].rationale}")
    lines.append(
        "suppress in place with `# tpulint: disable=RULE -- why` (same "
        "line or a comment line above); reason-less suppressions are "
        "BARE-SUPPRESS findings"
    )
    return "\n".join(lines)


def render_explain(rules, rule_id):
    """Full rationale for one rule (class docstring + one-liner), or
    None when the id is unknown."""
    rule = rules.get(rule_id.strip().upper())
    if rule is None:
        return None
    doc = (type(rule).__doc__ or "").strip("\n")
    lines = [f"{rule.id}: {rule.rationale}", ""]
    if doc:
        lines.append(doc)
    return "\n".join(lines)
