"""Multi-chip parallelism layer for the TPU-native framework.

The reference client stack has no model parallelism (SURVEY.md §2.4 note) —
sharding is a *server-side* concern there.  In this framework the server side
is in-repo (client_tpu.serve), so the parallelism layer is first-class:

- :func:`make_mesh` — build a ``jax.sharding.Mesh`` over the five axes
  ``dp``/``tp``/``sp``/``ep``/``pp`` (data / tensor / sequence-context /
  expert / pipeline parallel) from whatever devices exist.
- :mod:`client_tpu.parallel.ring_attention` — causal ring attention over the
  ``sp`` axis (blockwise flash accumulation + ``ppermute`` KV rotation) so
  long sequences shard across chips with KV traffic riding ICI.
- :mod:`client_tpu.parallel.pipeline` — GPipe pipeline parallelism over
  ``pp`` (shard_map'd ``lax.scan`` schedule with ``ppermute`` handoffs).
- Param/activation PartitionSpec builders used by the transformer model family
  (Megatron-style tensor parallel layout: attention sharded over heads, dense
  MLP over the hidden dimension, embedding over vocab; for MoE configs the
  expert dim shards over ``ep`` with each expert's hidden dim over ``tp``).

Everything here is pure ``jax.sharding`` + collectives: XLA inserts the
all-gathers/reduce-scatters; nothing is hand-scheduled.
"""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from client_tpu.parallel.ring_attention import ring_attention  # noqa: F401


def make_mesh(devices=None, dp=None, tp=None, sp=None, ep=None, pp=None):
    """Build a ("dp","tp","sp","ep","pp") Mesh over ``devices``.

    Axes: data / tensor / sequence(context) / expert / pipeline parallel.
    Unspecified axis sizes default to 1 (dp absorbs the remaining devices),
    so existing dp/tp/sp meshes are unchanged — the extra size-1 axes
    replicate trivially.  The product must equal the device count.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    tp = 1 if tp is None else tp
    sp = 1 if sp is None else sp
    ep = 1 if ep is None else ep
    pp = 1 if pp is None else pp
    denom = tp * sp * ep * pp
    if dp is None:
        if n % denom:
            raise ValueError(
                f"{n} devices not divisible by tp*sp*ep*pp={denom}"
            )
        dp = n // denom
    if dp * denom != n:
        raise ValueError(f"dp*tp*sp*ep*pp={dp * denom} != {n} devices")
    dev_array = np.asarray(devices).reshape(dp, tp, sp, ep, pp)
    return Mesh(dev_array, ("dp", "tp", "sp", "ep", "pp"))


def batch_spec():
    """Activation spec: batch over dp, sequence over sp."""
    return P("dp", "sp")


def logit_spec():
    return P("dp", "sp", "tp")


def param_specs(cfg):
    """PartitionSpecs for transformer params (see models/transformer.py).

    Megatron layout: q/k/v projections column-parallel over heads (tp),
    o projection row-parallel; MLP up/gate column-parallel over d_ff, down
    row-parallel; embedding and LM head sharded over vocab.  Norm scales are
    replicated.
    """
    layer = {
        "attn": {
            "wq": P(None, "tp"),
            "wk": P(None, "tp"),
            "wv": P(None, "tp"),
            "wo": P("tp", None),
        },
        "ln_attn": P(None),
        "ln_mlp": P(None),
    }
    if getattr(cfg, "n_experts", 0) > 0:
        # expert-parallel MoE: the expert dim shards over ep, each expert's
        # hidden dim over tp; the router is replicated (every device routes)
        layer["moe"] = {
            "router": P(None, None),
            "w_gate": P("ep", None, "tp"),
            "w_up": P("ep", None, "tp"),
            "w_down": P("ep", "tp", None),
        }
    else:
        layer["mlp"] = {
            "w_gate": P(None, "tp"),
            "w_up": P(None, "tp"),
            "w_down": P("tp", None),
        }
    return {
        "embed": P("tp", None),
        "layers": [layer for _ in range(cfg.n_layers)],
        "ln_f": P(None),
        "lm_head": P(None, "tp"),
    }


def named_shardings(mesh, specs):
    """Map a PartitionSpec pytree to NamedShardings on ``mesh``."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )
