"""GPipe-style pipeline parallelism over the mesh's ``pp`` axis.

The layer stack is split into S contiguous stages; each device along ``pp``
holds one stage's parameters (stacked with a leading stage dim sharded over
``pp``).  Microbatches flow through the pipeline with ``lax.ppermute``
activation handoffs riding ICI: at step t, stage s processes microbatch
t - s, so after M + S - 1 steps all M microbatches have crossed all stages
and the bubble is the classic (S-1)/(M+S-1) fraction.

Everything runs inside one ``jax.shard_map``-ed, jit-compiled program —
the schedule is a ``lax.scan``, the handoff a collective, nothing is
host-orchestrated.  Backward works by differentiating straight through the
scan + ppermute (grad of a ppermute is the reverse ppermute), which gives
correct full-batch gradients with recomputation — the 1F1B memory schedule
is the production refinement this trades away.

This completes the framework's parallelism portfolio (dp/tp/sp/ep/pp);
the reference client stack has none of it (SURVEY.md §2.4 note).
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from client_tpu._jax_compat import shard_map as _shard_map


def stack_stage_params(layers, n_stages):
    """[L] list of identical per-layer pytrees -> pytree with leading
    [S, L/S] dims, ready to shard over ``pp``."""
    n_layers = len(layers)
    if n_layers % n_stages:
        raise ValueError(
            f"{n_layers} layers not divisible by {n_stages} stages"
        )
    per = n_layers // n_stages
    stage_trees = [
        jax.tree.map(lambda *xs: jnp.stack(xs), *layers[s * per:(s + 1) * per])
        for s in range(n_stages)
    ]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_trees)


def pipeline_apply(stage_fn, stage_params, x, mesh, n_microbatches,
                   axis="pp", batch_axis="dp"):
    """Run ``x`` through the S-stage pipeline.

    Args:
      stage_fn: ``(stage_layers, x_mb) -> y_mb`` applying ONE stage's layer
        block to one microbatch; ``stage_layers`` leaves have a leading
        [L/S] dim (scan over it inside).  Must preserve the microbatch
        shape (activations hand off between stages unchanged).
      stage_params: pytree from :func:`stack_stage_params`, leaves
        [S, L/S, ...], laid out (or laid out by this call) over ``axis``.
      x: [B, ...] batch, B divisible by n_microbatches.
      mesh: mesh containing ``axis``.
      batch_axis: mesh axis the per-microbatch batch dim shards over
        (data parallelism *inside* the pipeline region); each dp slice
        pipelines its own microbatch shard.  Pass None to replicate.

    Within the pipeline region the non-stage dims of ``stage_params`` are
    replicated: tensor-parallel sharding inside a shard_map body needs
    hand-written collectives in ``stage_fn``, which this GPipe layer does
    not do — tp/ep compose only outside the region (embed / lm_head).

    Returns [B, ...] outputs, replicated over ``axis``, sharded over
    ``batch_axis``.
    """
    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    if batch % n_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by {n_microbatches} microbatches"
        )
    mb = batch // n_microbatches
    if batch_axis is not None and mesh.shape[batch_axis] > 1:
        if mb % mesh.shape[batch_axis]:
            raise ValueError(
                f"microbatch size {mb} not divisible by "
                f"{batch_axis}={mesh.shape[batch_axis]}"
            )
    else:
        batch_axis = None
    x_micro = x.reshape(n_microbatches, mb, *x.shape[1:])
    x_spec = P(None, batch_axis)

    param_specs = jax.tree.map(lambda _: P(axis), stage_params)

    def body(local_params, x_all):
        # local leaves are [1, L/S, ...]: drop the sharded stage dim
        local_params = jax.tree.map(lambda a: a[0], local_params)
        stage = lax.axis_index(axis)
        n_steps = n_microbatches + n_stages - 1
        state = jnp.zeros(x_all.shape[1:], x_all.dtype)  # inflight activation
        outputs = jnp.zeros_like(x_all)
        if hasattr(lax, "pcast"):
            # the scan body makes both carries pp-varying (stage params are
            # sharded over pp) — and dp-varying when the batch is sharded;
            # the zero-initialized carries must match.  `outputs` inherits
            # the batch variance from zeros_like(x_all); `state` is fresh.
            vary = (axis,) if batch_axis is None else (axis, batch_axis)
            state = lax.pcast(state, vary, to="varying")
            outputs = lax.pcast(outputs, (axis,), to="varying")

        def step(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (clamped: past-the-end steps feed
            # a stale microbatch whose output is never collected)
            feed = lax.dynamic_index_in_dim(
                x_all, jnp.clip(t, 0, n_microbatches - 1), keepdims=False
            )
            current = jnp.where(stage == 0, feed, state)
            y = stage_fn(local_params, current)
            # the last stage's step-t output is microbatch t-(S-1)
            out_idx = t - (n_stages - 1)
            valid = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            idx = jnp.clip(out_idx, 0, n_microbatches - 1)
            zeros = (0,) * y.ndim
            old = lax.dynamic_slice(outputs, (idx,) + zeros, (1,) + y.shape)
            outputs = lax.dynamic_update_slice(
                outputs, jnp.where(valid, y[None], old), (idx,) + zeros
            )
            # hand the activation to the next stage
            state = lax.ppermute(
                y, axis, [(i, (i + 1) % n_stages) for i in range(n_stages)]
            )
            return (state, outputs), None

        (state, outputs), _ = lax.scan(
            step, (state, outputs), jnp.arange(n_steps)
        )
        # only the last stage holds real outputs (zeros elsewhere): the psum
        # broadcasts them to every stage, making the result replicated
        return lax.psum(outputs, axis)

    fn = _shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
    )
    out = fn(stage_params, x_micro)
    return out.reshape(batch, *x.shape[1:])
