"""Causal ring attention over a sequence-parallel mesh axis.

Long-context support: Q/K/V are sharded along the sequence dimension across
the ``sp`` mesh axis.  Each device keeps its Q block resident and rotates the
K/V blocks around the ring with ``lax.ppermute`` (ICI neighbor exchange),
accumulating softmax results blockwise with the numerically-stable
flash-attention recurrence (running max ``m``, running denominator ``l``,
running weighted sum ``o``).  After ``sp`` steps every Q block has seen every
KV block and no device ever materialized the full [T, T] score matrix or the
full-length K/V.

Causality is enforced per block-pair: a KV block strictly "in the future" of
the Q block contributes nothing (fully masked); the diagonal block gets the
usual triangular mask.  All accumulation is float32 regardless of input dtype.

This is the framework's long-context primitive (the reference client has none
— SURVEY.md §5.7); it is used by the transformer model family's
sequence-parallel training/prefill path.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

_NEG = -1e30  # stand-in for -inf that keeps exp() NaN-free


def _block_accumulate(o, m, l, q, kb, vb, q_pos, kv_pos, scale, causal):
    """One flash-attention accumulation step against KV block (kb, vb).

    Layouts: q [B,H,Tq,D]; kb/vb [B,H,Tk,D]; o [B,H,Tq,D] f32;
    m/l [B,H,Tq,1] f32.  q_pos [Tq], kv_pos [Tk] are global token positions.
    """
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, kb, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        mask = q_pos[:, None] >= kv_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    blk_max = jnp.max(s, axis=-1, keepdims=True)
    new_m = jnp.maximum(m, blk_max)
    corr = jnp.exp(m - new_m)
    p = jnp.exp(s - new_m)
    new_l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum(
        "bhqk,bhkd->bhqd", p, vb.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    new_o = o * corr + pv
    return new_o, new_m, new_l


def ring_attention(q, k, v, axis_name="sp", causal=True, scale=None,
                   impl="plain"):
    """Per-shard ring attention body; call inside ``jax.shard_map``.

    Args:
      q, k, v: [B, T_local, H, D] — the sequence dimension is the local shard
        of a global sequence laid out contiguously across ``axis_name``.
      axis_name: mesh axis carrying the sequence shards.
      causal: apply the causal mask using *global* token positions.
      scale: score scale; defaults to D**-0.5.
      impl: "plain" — the per-step block accumulate is XLA einsums
        materializing one [Tloc, Tloc] score block; "flash" — each ring
        step runs the Pallas kernel (client_tpu.ops) over the local pair
        and steps merge by log-sum-exp, so per-step memory is O(block)
        even at long local shards.  Block-causality makes the two modes
        line up exactly: the diagonal step is the kernel's own causal
        mask, past steps are unmasked, future steps are skipped.

    Returns [B, T_local, H, D] in q's dtype.
    """
    if impl == "flash":
        return _ring_attention_flash(q, k, v, axis_name, causal, scale)
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t_loc, h, d = q.shape
    if scale is None:
        scale = d ** -0.5

    qh = q.transpose(0, 2, 1, 3)  # [B,H,T,D]
    kb = k.transpose(0, 2, 1, 3)
    vb = v.transpose(0, 2, 1, 3)

    o = jnp.zeros(qh.shape, jnp.float32)
    m = jnp.full((b, h, t_loc, 1), _NEG, jnp.float32)
    l = jnp.zeros((b, h, t_loc, 1), jnp.float32)
    # mark the constant-initialized accumulators as device-varying so both
    # lax.cond branches below agree on varying-axis types under shard_map
    varying = tuple(jax.typeof(q).vma) if hasattr(jax, "typeof") else ()
    if varying:
        o, m, l = (lax.pcast(x, varying, to="varying") for x in (o, m, l))
    q_pos = idx * t_loc + jnp.arange(t_loc)

    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        kv_idx = (idx - step) % n
        kv_pos = kv_idx * t_loc + jnp.arange(t_loc)
        if causal:
            # KV blocks strictly in this Q block's future contribute exactly
            # nothing — skip their einsums (kv_idx is device-constant under
            # SPMD, so each device runs only its selected branch)
            o, m, l = lax.cond(
                kv_idx > idx,
                lambda o, m, l, *_: (o, m, l),
                functools.partial(_block_accumulate, scale=scale, causal=True),
                o, m, l, qh, kb, vb, q_pos, kv_pos,
            )
        else:
            o, m, l = _block_accumulate(
                o, m, l, qh, kb, vb, q_pos, kv_pos, scale, False
            )
        if step != n - 1:
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)

    out = (o / l).astype(q.dtype)
    return out.transpose(0, 2, 1, 3)


def _ring_attention_flash(q, k, v, axis_name, causal, scale):
    """Ring schedule with the Pallas flash kernel as the per-step engine.

    Each step computes a self-contained (out_s, lse_s) for the resident Q
    shard against the rotating KV shard; partial results merge with the
    exact softmax-combine ``o ← o·α + o_s·α_s`` where the α's renormalize
    by ``logaddexp(lse, lse_s)``.  Future KV shards are skipped (their lse
    is −inf and contributes nothing, so the cond is purely a compute save).
    """
    from client_tpu.ops.flash_attention import flash_attention_with_lse

    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, t_loc, h, d = q.shape
    if scale is None:
        scale = d ** -0.5

    acc = jnp.zeros((b, h, t_loc, d), jnp.float32)
    lse = jnp.full((b, h, t_loc, 1), _NEG, jnp.float32)
    varying = tuple(jax.typeof(q).vma) if hasattr(jax, "typeof") else ()
    if varying:
        acc, lse = (lax.pcast(x, varying, to="varying") for x in (acc, lse))
    kb, vb = k, v

    def step_pair(kb_vb, step_causal):
        kb_, vb_ = kb_vb
        out_s, lse_s = flash_attention_with_lse(
            q, kb_, vb_, causal=step_causal, scale=scale
        )
        return out_s.transpose(0, 2, 1, 3).astype(jnp.float32), lse_s

    def merge(acc, lse, out_s, lse_s):
        new_lse = jnp.logaddexp(lse, lse_s)
        return (
            acc * jnp.exp(lse - new_lse) + out_s * jnp.exp(lse_s - new_lse),
            new_lse,
        )

    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        kv_idx = (idx - step) % n
        if causal:
            def on_diag(acc, lse, kb_, vb_):
                out_s, lse_s = step_pair((kb_, vb_), True)
                return merge(acc, lse, out_s, lse_s)

            def off_diag(acc, lse, kb_, vb_):
                out_s, lse_s = step_pair((kb_, vb_), False)
                return merge(acc, lse, out_s, lse_s)

            def skip(acc, lse, kb_, vb_):
                return acc, lse

            # three-way: strictly-future shard contributes nothing; the
            # diagonal shard uses the kernel's local causal mask; past
            # shards attend fully (global positions never needed)
            acc, lse = lax.cond(
                kv_idx > idx,
                skip,
                lambda a, l, kb_, vb_: lax.cond(
                    kv_idx == idx, on_diag, off_diag, a, l, kb_, vb_
                ),
                acc, lse, kb, vb,
            )
        else:
            out_s, lse_s = step_pair((kb, vb), False)
            acc, lse = merge(acc, lse, out_s, lse_s)
        if step != n - 1:
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)

    return acc.astype(q.dtype).transpose(0, 2, 1, 3)


def plain_attention(q, k, v, causal=True, scale=None):
    """Single-shard reference attention; same [B,T,H,D] interface."""
    b, t, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        kt = k.shape[1]
        # offset so the last q row attends to the full kv length (decode case)
        pos_q = jnp.arange(t) + (kt - t)
        mask = pos_q[:, None] >= jnp.arange(kt)[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, causal=True, scale=None,
                           impl="plain"):
    """shard_map wrapper: global [B,T,H,D] arrays, T sharded over ``sp``.

    Batch rides ``dp``; heads ride ``tp``; D is replicated.  The body sees
    local blocks and exchanges KV over the ring; ``impl="flash"`` runs each
    ring step through the Pallas kernel (O(block) per-step memory).
    """
    spec = P("dp", "sp", "tp", None)
    # check_vma: Pallas INTERPRET mode (the off-TPU test path) lowers to
    # dynamic_slice with invariant index operands, which the varying-axis
    # checker rejects — disable it only there; compiled TPU runs keep the
    # checker for both impls.
    interpret = jax.default_backend() != "tpu"
    fn = jax.shard_map(
        lambda a, b_, c: ring_attention(a, b_, c, "sp", causal, scale, impl),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=not (impl == "flash" and interpret),
    )
    return fn(q, k, v)
