"""Causal ring attention over a sequence-parallel mesh axis.

Long-context support: Q/K/V are sharded along the sequence dimension across
the ``sp`` mesh axis.  Each device keeps its Q block resident and rotates the
K/V blocks around the ring with ``lax.ppermute`` (ICI neighbor exchange),
accumulating softmax results blockwise with the numerically-stable
flash-attention recurrence (running max ``m``, running denominator ``l``,
running weighted sum ``o``).  After ``sp`` steps every Q block has seen every
KV block and no device ever materialized the full [T, T] score matrix or the
full-length K/V.

Causality is enforced per block-pair: a KV block strictly "in the future" of
the Q block contributes nothing (fully masked); the diagonal block gets the
usual triangular mask.  All accumulation is float32 regardless of input dtype.

This is the framework's long-context primitive (the reference client has none
— SURVEY.md §5.7); it is used by the transformer model family's
sequence-parallel training/prefill path.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from client_tpu._jax_compat import shard_map as _shard_map

_NEG = -1e30  # stand-in for -inf that keeps exp() NaN-free


def _block_accumulate(o, m, l, q, kb, vb, q_pos, kv_pos, scale, causal):
    """One flash-attention accumulation step against KV block (kb, vb).

    Layouts: q [B,H,Tq,D]; kb/vb [B,H,Tk,D]; o [B,H,Tq,D] f32;
    m/l [B,H,Tq,1] f32.  q_pos [Tq], kv_pos [Tk] are global token positions.
    """
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, kb, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        mask = q_pos[:, None] >= kv_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    blk_max = jnp.max(s, axis=-1, keepdims=True)
    new_m = jnp.maximum(m, blk_max)
    corr = jnp.exp(m - new_m)
    p = jnp.exp(s - new_m)
    new_l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum(
        "bhqk,bhkd->bhqd", p, vb.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    new_o = o * corr + pv
    return new_o, new_m, new_l


def _ring_schedule(state, k, v, axis_name, causal, step_fn):
    """THE ring schedule, shared by both impls: rotate KV around the ring
    with ppermute, calling ``step_fn(state, kb, vb, kv_idx, idx)`` for every
    non-future shard pair (under causality, strictly-future KV shards are
    skipped — they contribute exactly nothing).  ``state`` is any pytree;
    step_fn owns the accumulate/merge semantics."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    kb, vb = k, v
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        kv_idx = (idx - step) % n
        if causal:
            # kv_idx is device-constant under SPMD: each device runs only
            # its selected branch, so the skip really saves the compute
            state = lax.cond(
                kv_idx > idx,
                lambda st, *_: st,
                lambda st, kb_, vb_: step_fn(st, kb_, vb_, kv_idx, idx),
                state, kb, vb,
            )
        else:
            state = step_fn(state, kb, vb, kv_idx, idx)
        if step != n - 1:
            kb = lax.ppermute(kb, axis_name, perm)
            vb = lax.ppermute(vb, axis_name, perm)
    return state


def _varying_full(q, shapes_dtypes):
    """Constant-filled accumulators (shape, dtype, fill triples) marked with
    q's device-varying axes so the lax.cond branches' varying-axis types
    agree under shard_map."""
    arrs = [jnp.full(sh, fill, dt) for sh, dt, fill in shapes_dtypes]
    varying = tuple(jax.typeof(q).vma) if hasattr(jax, "typeof") else ()
    if varying:
        arrs = [lax.pcast(a, varying, to="varying") for a in arrs]
    return arrs


def ring_attention(q, k, v, axis_name="sp", causal=True, scale=None,
                   impl="plain"):
    """Per-shard ring attention body; call inside ``jax.shard_map``.

    Args:
      q, k, v: [B, T_local, H, D] — the sequence dimension is the local shard
        of a global sequence laid out contiguously across ``axis_name``.
      axis_name: mesh axis carrying the sequence shards.
      causal: apply the causal mask using *global* token positions.
      scale: score scale; defaults to D**-0.5.
      impl: "plain" — the per-step block accumulate is XLA einsums
        materializing one [Tloc, Tloc] score block; "flash" — each ring
        step runs the Pallas kernel (client_tpu.ops) over the local pair
        and steps merge by log-sum-exp, so per-step memory is O(block)
        even at long local shards.  Block-causality makes the two modes
        line up exactly: the diagonal step is the kernel's own causal
        mask, past steps are unmasked, future steps are skipped.

    Returns [B, T_local, H, D] in q's dtype.
    """
    if impl == "flash":
        return _ring_attention_flash(q, k, v, axis_name, causal, scale)
    b, t_loc, h, d = q.shape
    if scale is None:
        scale = d ** -0.5

    qh = q.transpose(0, 2, 1, 3)  # [B,H,T,D]
    o, m, l = _varying_full(q, [
        (qh.shape, jnp.float32, 0.0),
        ((b, h, t_loc, 1), jnp.float32, _NEG),
        ((b, h, t_loc, 1), jnp.float32, 0.0),
    ])
    # transpose KV once; the schedule rotates whatever layout it is given
    q_pos = lax.axis_index(axis_name) * t_loc + jnp.arange(t_loc)

    def step_fn(state, kb, vb, kv_idx, idx):
        o, m, l = state
        kv_pos = kv_idx * t_loc + jnp.arange(t_loc)
        return _block_accumulate(
            o, m, l, qh, kb, vb, q_pos, kv_pos, scale, causal,
        )

    o, m, l = _ring_schedule(
        (o, m, l), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
        axis_name, causal, step_fn,
    )
    out = (o / l).astype(q.dtype)
    return out.transpose(0, 2, 1, 3)


def _ring_attention_flash(q, k, v, axis_name, causal, scale):
    """Ring schedule with the Pallas flash kernel as the per-step engine.

    Each step computes a self-contained (out_s, lse_s) for the resident Q
    shard against the rotating KV shard; partial results merge with the
    exact softmax-combine ``o ← o·α + o_s·α_s`` where the α's renormalize
    by ``logaddexp(lse, lse_s)``.  Future KV shards are skipped (their lse
    is −inf and contributes nothing, so the cond is purely a compute save).
    """
    from client_tpu.ops.flash_attention import flash_attention_with_lse

    b, t_loc, h, d = q.shape
    if scale is None:
        scale = d ** -0.5

    acc, lse = _varying_full(q, [
        ((b, h, t_loc, d), jnp.float32, 0.0),
        ((b, h, t_loc, 1), jnp.float32, _NEG),
    ])

    def merge(acc, lse, out_s, lse_s):
        new_lse = jnp.logaddexp(lse, lse_s)
        return (
            acc * jnp.exp(lse - new_lse) + out_s * jnp.exp(lse_s - new_lse),
            new_lse,
        )

    def step_fn(state, kb, vb, kv_idx, idx):
        acc, lse = state

        def run(step_causal, a, l, kb_, vb_):
            out_s, lse_s = flash_attention_with_lse(
                q, kb_, vb_, causal=step_causal, scale=scale
            )
            out_s = out_s.transpose(0, 2, 1, 3).astype(jnp.float32)
            return merge(a, l, out_s, lse_s)

        if causal:
            # the diagonal shard uses the kernel's local causal mask; past
            # shards attend fully (global positions never needed — the
            # schedule already skipped strictly-future shards)
            return lax.cond(
                kv_idx == idx,
                functools.partial(run, True),
                functools.partial(run, False),
                acc, lse, kb, vb,
            )
        return run(False, acc, lse, kb, vb)

    acc, lse = _ring_schedule((acc, lse), k, v, axis_name, causal, step_fn)
    return acc.astype(q.dtype).transpose(0, 2, 1, 3)


def plain_attention(q, k, v, causal=True, scale=None):
    """Single-shard reference attention; same [B,T,H,D] interface."""
    b, t, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        kt = k.shape[1]
        # offset so the last q row attends to the full kv length (decode case)
        pos_q = jnp.arange(t) + (kt - t)
        mask = pos_q[:, None] >= jnp.arange(kt)[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, causal=True, scale=None,
                           impl="plain"):
    """shard_map wrapper: global [B,T,H,D] arrays, T sharded over ``sp``.

    Batch rides ``dp``; heads ride ``tp``; D is replicated.  The body sees
    local blocks and exchanges KV over the ring; ``impl="flash"`` runs each
    ring step through the Pallas kernel (O(block) per-step memory).
    """
    spec = P("dp", "sp", "tp", None)
    # check_vma: Pallas INTERPRET mode (the off-TPU test path) lowers to
    # dynamic_slice with invariant index operands, which the varying-axis
    # checker rejects — disable it only there; compiled TPU runs keep the
    # checker for both impls.
    interpret = jax.default_backend() != "tpu"
    fn = _shard_map(
        lambda a, b_, c: ring_attention(a, b_, c, "sp", causal, scale, impl),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=not (impl == "flash" and interpret),
    )
    return fn(q, k, v)
