"""traceview: join client + server + peer trace files by trace id and
print per-trace timelines with critical-path attribution.

The tracing subsystem writes JSON-lines span records from three places —
clients (``client_tpu.tracing``), servers (``client_tpu.serve.tracing``)
and the fleet tier's peer spans — often into separate files on separate
machines.  This tool is the join::

    python -m client_tpu.traceview client.jsonl replica0.jsonl replica1.jsonl
    python -m client_tpu.traceview --trace 4f2a... --format json *.jsonl

For every trace id it prints the spans in timeline order (source, model,
endpoint/peer tags, per-event offsets from the trace's first timestamp)
and a **critical-path attribution** line splitting the end-to-end latency
into:

- ``queue``   — server-side scheduling wait (QUEUE_START → QUEUE_END),
- ``compute`` — model execution (COMPUTE_START → COMPUTE_END, peer-serve
  spans excluded),
- ``peer``    — fleet tier fetches (PEER_START → PEER_END: prefix/cache/
  sequence lookups, durability pushes),
- ``wire``    — the remainder of the client-observed duration not inside
  any server span (serialization + network + client overhead).

A trace that spans a replica SIGKILL (client attempt spans on two
endpoints, both replicas' server spans, the survivor's peer
``sequence_lookup`` and ``__seq_resume__`` marker) renders as ONE
timeline — the artifact the three-replica chaos acceptance asserts on.

``--format json`` emits the joined structure (one object per trace) for
scripting; everything in this module is stdlib-only.
"""

import argparse
import json
import sys

from client_tpu.tracing import read_trace_file

__all__ = ["join_traces", "load_records", "critical_path", "render_trace",
           "main"]


def load_records(paths):
    """All span records from *paths* (JSON-lines trace files), in file
    order.  Unreadable files raise; unparsable lines were never written
    by the tracers and raise too — garbage in a postmortem artifact
    should be loud."""
    records = []
    for path in paths:
        records.extend(read_trace_file(path))
    return records


def _events(record):
    """(name, ns, extra) tuples of one record's timestamps."""
    out = []
    for ts in record.get("timestamps") or ():
        name = ts.get("name")
        ns = ts.get("ns")
        if name is None or ns is None:
            continue
        out.append((str(name), int(ns), ts))
    return out


def _span_bounds(record):
    """(first_ns, last_ns) over a record's events, or None."""
    events = _events(record)
    if not events:
        return None
    times = [ns for _name, ns, _e in events]
    return min(times), max(times)


def _interval(record, start_name, end_name):
    """Duration ns between the first *start_name* and the last
    *end_name* event (0 when either is missing)."""
    start = end = None
    for name, ns, _extra in _events(record):
        if name == start_name and start is None:
            start = ns
        if name == end_name:
            end = ns
    if start is None or end is None or end < start:
        return 0
    return end - start


def _is_peer(record):
    return str(record.get("model_name", "")).startswith("__peer_")


def _is_tick(record):
    return str(record.get("model_name", "")).startswith("__lm_")


def join_traces(records):
    """Group span records by trace id -> ``{trace_id: [records]}`` with
    each trace's records sorted by first timestamp.  Records with no
    timestamps (or no trace id) are dropped — nothing to place on a
    timeline."""
    traces = {}
    for record in records:
        trace_id = record.get("trace_id")
        if not trace_id or _span_bounds(record) is None:
            continue
        traces.setdefault(trace_id, []).append(record)
    for spans in traces.values():
        spans.sort(key=lambda r: _span_bounds(r)[0])
    return traces


def _merged_length(intervals):
    """Total ns covered by the union of (start, end) intervals —
    overlapping server spans (ensemble steps, resumes) must not
    double-count."""
    total = 0
    last_end = None
    for start, end in sorted(intervals):
        if last_end is None or start > last_end:
            total += end - start
            last_end = end
        elif end > last_end:
            total += end - last_end
            last_end = end
    return total


def critical_path(spans):
    """Attribute one trace's end-to-end time -> dict of millisecond
    figures.

    ``total`` is the client-observed duration (CLIENT_REQUEST_START →
    the last CLIENT_REQUEST_END; multi-request traces — a pinned
    sequence — sum their per-request client spans) falling back to the
    trace's full event extent.  ``queue``/``compute`` sum the server
    request spans' phase intervals, ``peer`` the peer spans' durations,
    and ``wire`` is the client time not covered by any server span."""
    client_intervals = []
    server_intervals = []
    queue_ns = compute_ns = peer_ns = 0
    for record in spans:
        source = record.get("source")
        if source == "client":
            dur = _interval(record, "CLIENT_REQUEST_START",
                            "CLIENT_REQUEST_END")
            bounds = _span_bounds(record)
            if dur:
                client_intervals.append((bounds[0], bounds[0] + dur))
            elif bounds is not None:
                client_intervals.append(bounds)
            continue
        if _is_peer(record):
            peer_ns += (
                _interval(record, "PEER_START", "PEER_END")
                or _interval(record, "COMPUTE_START", "COMPUTE_END")
            )
            continue
        if _is_tick(record):
            continue  # scheduler ticks are engine-wide, not per-request
        # server request span
        queue_ns += _interval(record, "QUEUE_START", "QUEUE_END")
        compute_ns += _interval(record, "COMPUTE_START", "COMPUTE_END")
        bounds = _span_bounds(record)
        if bounds is not None:
            server_intervals.append(bounds)
    if client_intervals:
        total_ns = _merged_length(client_intervals)
    else:
        bounds = [b for b in map(_span_bounds, spans) if b is not None]
        total_ns = (
            max(e for _s, e in bounds) - min(s for s, _e in bounds)
            if bounds else 0
        )
    server_ns = _merged_length(server_intervals)
    wire_ns = max(total_ns - server_ns, 0) if client_intervals else 0
    to_ms = 1e-6
    return {
        "total_ms": total_ns * to_ms,
        "queue_ms": queue_ns * to_ms,
        "compute_ms": compute_ns * to_ms,
        "peer_ms": peer_ns * to_ms,
        "wire_ms": wire_ns * to_ms,
    }


def _span_label(record):
    source = record.get("source", "?")
    name = record.get("model_name", "")
    bits = [f"{source:<6}", name]
    tags = record.get("tags") or {}
    endpoint = next(
        (e.get("endpoint") for _n, _ns, e in _events(record)
         if e.get("endpoint")),
        None,
    )
    if endpoint:
        bits.append(f"endpoint={endpoint}")
    for key in ("peer", "op", "hit", "stored", "bytes", "breaker",
                "sequence_id", "resumed_trace", "resumed_sequence"):
        if key in tags:
            bits.append(f"{key}={tags[key]}")
    if record.get("tenant"):
        bits.append(f"tenant={record['tenant']}")
    if record.get("error"):
        bits.append(f"ERROR={record['error']}")
    return " ".join(str(b) for b in bits)


def trace_summary(trace_id, spans):
    """The joined, attribution-annotated structure of one trace (what
    ``--format json`` emits per trace)."""
    t0 = min(_span_bounds(r)[0] for r in spans)
    models = sorted({
        str(r.get("model_name"))
        for r in spans
        if r.get("model_name") and not _is_peer(r) and not _is_tick(r)
    })
    sources = sorted({str(r.get("source", "?")) for r in spans})
    return {
        "trace_id": trace_id,
        "start_ns": t0,
        "spans": len(spans),
        "sources": sources,
        "models": models,
        "critical_path": critical_path(spans),
        "records": spans,
    }


def render_trace(trace_id, spans, out):
    """Human timeline for one trace."""
    summary = trace_summary(trace_id, spans)
    t0 = summary["start_ns"]
    cp = summary["critical_path"]
    out.write(
        f"trace {trace_id}  spans={len(spans)} "
        f"sources={','.join(summary['sources'])} "
        f"models={','.join(summary['models']) or '-'}\n"
    )
    out.write(
        "  critical path: total {total_ms:.3f} ms = "
        "queue {queue_ms:.3f} | compute {compute_ms:.3f} | "
        "peer-fetch {peer_ms:.3f} | wire {wire_ms:.3f}\n".format(**cp)
    )
    for record in spans:
        bounds = _span_bounds(record)
        out.write(
            f"  [{(bounds[0] - t0) / 1e6:9.3f} ms "
            f"+{(bounds[1] - bounds[0]) / 1e6:8.3f} ms] "
            f"{_span_label(record)}\n"
        )
        for name, ns, _extra in _events(record):
            out.write(f"      {(ns - t0) / 1e6:9.3f} ms  {name}\n")


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m client_tpu.traceview",
        description="Join client/server/peer trace files by trace id and "
                    "print per-trace timelines with critical-path "
                    "attribution.",
    )
    parser.add_argument("files", nargs="+", help="JSON-lines trace files")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="text timelines (default) or one JSON object per trace "
             "for scripting",
    )
    parser.add_argument(
        "--trace", default=None,
        help="only the trace with this id (prefix match)",
    )
    parser.add_argument(
        "--min-spans", type=int, default=1,
        help="skip traces with fewer spans (default 1)",
    )
    args = parser.parse_args(argv)
    try:
        records = load_records(args.files)
    except (OSError, ValueError) as e:
        print(f"traceview: {e}", file=sys.stderr)
        return 2
    traces = join_traces(records)
    selected = sorted(
        (
            (trace_id, spans)
            for trace_id, spans in traces.items()
            if len(spans) >= args.min_spans
            and (args.trace is None or trace_id.startswith(args.trace))
        ),
        key=lambda pair: _span_bounds(pair[1][0])[0],
    )
    if args.format == "json":
        for trace_id, spans in selected:
            sys.stdout.write(
                json.dumps(trace_summary(trace_id, spans),
                           separators=(",", ":")) + "\n"
            )
        return 0
    if not selected:
        print("no traces matched", file=sys.stderr)
        return 1
    for trace_id, spans in selected:
        render_trace(trace_id, spans, sys.stdout)
        sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
