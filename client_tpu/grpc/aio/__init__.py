"""asyncio gRPC client — mirror of client_tpu.grpc over ``grpc.aio``.

Capability parity with ``tritonclient.grpc.aio`` (reference
src/python/library/tritonclient/grpc/aio/__init__.py:34-772): every RPC as a
coroutine, plus ``stream_infer`` which maps an async iterator of requests onto
the bidirectional ModelStreamInfer stream and yields (result, error) tuples.
"""

import grpc

from client_tpu import resilience as _resilience
from client_tpu import tracing as _tracing
from client_tpu._grpc_infer import (  # noqa: F401
    InferResult,
    build_infer_request,
)
from client_tpu._grpc_service import build_stubs
from client_tpu._infer_types import InferInput, InferRequestedOutput  # noqa: F401
from client_tpu._proto import inference_pb2 as pb
from client_tpu.grpc import (
    KeepAliveOptions,  # noqa: F401
    _attempt_timeout,
    _channel_options,
    _grpc_compression,
    _metadata,
    _stamp_tenant,
    raise_error_grpc,
)
from client_tpu.utils import (
    SERVER_NOT_READY,
    SERVER_READY,
    SERVER_UNREACHABLE,
    InferenceServerException,
    raise_error,
)

__all__ = [
    "InferenceServerClient",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "KeepAliveOptions",
]


class InferenceServerClient:
    """asyncio client for every GRPCInferenceService RPC."""

    def __init__(
        self,
        url,
        verbose=False,
        ssl=False,
        root_certificates=None,
        private_key=None,
        certificate_chain=None,
        creds=None,
        keepalive_options=None,
        channel_args=None,
        retry_policy=None,
        tracer=None,
        tenant=None,
    ):
        options = _channel_options(keepalive_options, channel_args)
        if creds is not None:
            self._channel = grpc.aio.secure_channel(url, creds, options=options)
        elif ssl:
            rc = pk = cc = None
            if root_certificates:
                with open(root_certificates, "rb") as f:
                    rc = f.read()
            if private_key:
                with open(private_key, "rb") as f:
                    pk = f.read()
            if certificate_chain:
                with open(certificate_chain, "rb") as f:
                    cc = f.read()
            credentials = grpc.ssl_channel_credentials(rc, pk, cc)
            self._channel = grpc.aio.secure_channel(url, credentials, options=options)
        else:
            self._channel = grpc.aio.insecure_channel(url, options=options)
        self._stubs = build_stubs(self._channel)
        self._endpoint = url  # host:port identity (trace attempt spans)
        self._verbose = verbose
        # Opt-in resilience for unary RPCs; None keeps single-attempt
        # behavior.  stream_infer is never retried (replay would re-send
        # every request the iterator already produced).
        self._retry_policy = retry_policy
        # Opt-in tracing (client_tpu.tracing.ClientTracer): client spans +
        # traceparent propagation over gRPC metadata.
        self._tracer = tracer
        # Tenant identity stamped on every verb (sync-client semantics).
        self._tenant = None if tenant is None else str(tenant)

    async def close(self):
        await self._channel.close()

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()

    async def _call(self, name, request, headers=None, client_timeout=None,
                    trace=None, **kw):
        if self._retry_policy is None:
            return await self._attempt_once(
                name, request, headers, client_timeout, trace, **kw
            )

        async def attempt(timeout_s):
            timeout = _attempt_timeout(client_timeout, timeout_s)
            return await self._attempt_once(
                name, request, headers, timeout, trace, **kw
            )

        return await _resilience.acall_with_retry(attempt, self._retry_policy)

    async def _attempt_once(self, name, request, headers, client_timeout,
                            trace, **kw):
        """One RPC attempt in a trace attempt span — retries show as
        repeated ATTEMPT_START/ATTEMPT_END pairs."""
        with _tracing.attempt_span(trace, endpoint=self._endpoint):
            return await self._call_once(
                name, request, headers, client_timeout, **kw
            )

    async def _call_once(self, name, request, headers=None, client_timeout=None, **kw):
        headers = _stamp_tenant(headers, self._tenant)
        if self._verbose:
            print(f"{name}, metadata {headers}\n{request}")
        try:
            response = await self._stubs[name](
                request, metadata=_metadata(headers), timeout=client_timeout, **kw
            )
            if self._verbose:
                print(response)
            return response
        except grpc.RpcError as e:
            raise_error_grpc(e)

    @staticmethod
    def _maybe_json(response, as_json):
        if not as_json:
            return response
        from google.protobuf import json_format

        return json_format.MessageToDict(response, preserving_proto_field_name=True)

    # -- health --------------------------------------------------------------
    # Health verbs answer False on transport errors instead of raising
    # (tritonclient reference semantics): probes must be safe to poll
    # against a down server.  They bypass the retry policy (_call_once) —
    # an unavailable answer IS the probe result, not a failure to retry.

    async def is_server_live(self, headers=None, client_timeout=None):
        try:
            r = await self._call_once(
                "ServerLive", pb.ServerLiveRequest(), headers, client_timeout
            )
        except InferenceServerException:
            return False
        return r.live

    async def is_server_ready(self, headers=None, client_timeout=None):
        try:
            r = await self._call_once(
                "ServerReady", pb.ServerReadyRequest(), headers, client_timeout
            )
        except InferenceServerException:
            return False
        return r.ready

    async def server_state(self, headers=None, client_timeout=None):
        """READY / NOT_READY / UNREACHABLE (client_tpu.utils constants) —
        a draining server answers ready=False (NOT_READY), a dead one
        fails the RPC (UNREACHABLE); same contract as the sync client."""
        try:
            r = await self._call_once(
                "ServerReady", pb.ServerReadyRequest(), headers, client_timeout
            )
        except InferenceServerException:
            return SERVER_UNREACHABLE
        return SERVER_READY if r.ready else SERVER_NOT_READY

    async def is_model_ready(
        self, model_name, model_version="", headers=None, client_timeout=None
    ):
        try:
            r = await self._call_once(
                "ModelReady",
                pb.ModelReadyRequest(name=model_name, version=model_version),
                headers,
                client_timeout,
            )
        except InferenceServerException:
            return False
        return r.ready

    # -- metadata / config / repository --------------------------------------

    async def get_server_metadata(self, headers=None, as_json=False, client_timeout=None):
        r = await self._call(
            "ServerMetadata", pb.ServerMetadataRequest(), headers, client_timeout
        )
        return self._maybe_json(r, as_json)

    async def get_model_metadata(
        self, model_name, model_version="", headers=None, as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "ModelMetadata",
            pb.ModelMetadataRequest(name=model_name, version=model_version),
            headers,
            client_timeout,
        )
        return self._maybe_json(r, as_json)

    async def get_model_config(
        self, model_name, model_version="", headers=None, as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "ModelConfig",
            pb.ModelConfigRequest(name=model_name, version=model_version),
            headers,
            client_timeout,
        )
        return self._maybe_json(r, as_json)

    async def get_model_repository_index(
        self, headers=None, as_json=False, client_timeout=None
    ):
        r = await self._call(
            "RepositoryIndex", pb.RepositoryIndexRequest(), headers, client_timeout
        )
        return self._maybe_json(r, as_json)

    async def load_model(
        self, model_name, headers=None, config=None, files=None, client_timeout=None
    ):
        import json as _json

        request = pb.RepositoryModelLoadRequest(model_name=model_name)
        if config is not None:
            request.parameters["config"].string_param = (
                config if isinstance(config, str) else _json.dumps(config)
            )
        for path, content in (files or {}).items():
            request.parameters[path].bytes_param = content
        await self._call("RepositoryModelLoad", request, headers, client_timeout)

    async def unload_model(
        self, model_name, headers=None, unload_dependents=False, client_timeout=None
    ):
        request = pb.RepositoryModelUnloadRequest(model_name=model_name)
        request.parameters["unload_dependents"].bool_param = unload_dependents
        await self._call("RepositoryModelUnload", request, headers, client_timeout)

    # -- statistics ----------------------------------------------------------

    async def get_inference_statistics(
        self, model_name="", model_version="", headers=None, as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "ModelStatistics",
            pb.ModelStatisticsRequest(name=model_name, version=model_version),
            headers,
            client_timeout,
        )
        return self._maybe_json(r, as_json)

    # -- trace / log settings (parity with the sync client; reference
    #    grpc/aio/__init__.py update_trace_settings..get_log_settings) -------

    async def update_trace_settings(
        self, model_name="", settings=None, headers=None, as_json=False,
        client_timeout=None,
    ):
        from client_tpu.grpc import build_trace_setting_request

        request = build_trace_setting_request(model_name, settings)
        r = await self._call("TraceSetting", request, headers, client_timeout)
        return self._maybe_json(r, as_json)

    async def get_trace_settings(
        self, model_name="", headers=None, as_json=False, client_timeout=None
    ):
        r = await self._call(
            "TraceSetting",
            pb.TraceSettingRequest(model_name=model_name),
            headers,
            client_timeout,
        )
        return self._maybe_json(r, as_json)

    async def update_log_settings(
        self, settings, headers=None, as_json=False, client_timeout=None
    ):
        from client_tpu.grpc import build_log_settings_request

        request = build_log_settings_request(settings)
        r = await self._call("LogSettings", request, headers, client_timeout)
        return self._maybe_json(r, as_json)

    async def get_log_settings(
        self, headers=None, as_json=False, client_timeout=None
    ):
        r = await self._call(
            "LogSettings", pb.LogSettingsRequest(), headers, client_timeout
        )
        return self._maybe_json(r, as_json)

    # -- shared memory -------------------------------------------------------

    async def get_system_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        r = await self._call(
            "SystemSharedMemoryStatus",
            pb.SystemSharedMemoryStatusRequest(name=region_name),
            headers,
            client_timeout,
        )
        return self._maybe_json(r, as_json)

    async def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, client_timeout=None
    ):
        await self._call(
            "SystemSharedMemoryRegister",
            pb.SystemSharedMemoryRegisterRequest(
                name=name, key=key, offset=offset, byte_size=byte_size
            ),
            headers,
            client_timeout,
        )

    async def unregister_system_shared_memory(
        self, name="", headers=None, client_timeout=None
    ):
        await self._call(
            "SystemSharedMemoryUnregister",
            pb.SystemSharedMemoryUnregisterRequest(name=name),
            headers,
            client_timeout,
        )

    async def get_tpu_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        r = await self._call(
            "TpuSharedMemoryStatus",
            pb.TpuSharedMemoryStatusRequest(name=region_name),
            headers,
            client_timeout,
        )
        return self._maybe_json(r, as_json)

    async def register_tpu_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, client_timeout=None
    ):
        await self._call(
            "TpuSharedMemoryRegister",
            pb.TpuSharedMemoryRegisterRequest(
                name=name,
                raw_handle=raw_handle,
                device_id=device_id,
                byte_size=byte_size,
            ),
            headers,
            client_timeout,
        )

    async def unregister_tpu_shared_memory(
        self, name="", headers=None, client_timeout=None
    ):
        await self._call(
            "TpuSharedMemoryUnregister",
            pb.TpuSharedMemoryUnregisterRequest(name=name),
            headers,
            client_timeout,
        )

    # -- inference -----------------------------------------------------------

    async def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        client_timeout=None,
        headers=None,
        compression_algorithm=None,
        parameters=None,
    ):
        with _tracing.client_span(self._tracer, model_name) as trace:
            request = build_infer_request(
                model_name,
                inputs,
                model_version,
                outputs,
                request_id,
                sequence_id,
                sequence_start,
                sequence_end,
                priority,
                timeout,
                parameters,
            )
            if trace is not None:
                trace.event("CLIENT_SERIALIZE_END")
                headers = dict(headers or {})
                headers["traceparent"] = trace.traceparent()
            response = await self._call(
                "ModelInfer",
                request,
                headers,
                client_timeout,
                trace=trace,
                compression=_grpc_compression(compression_algorithm),
            )
            return InferResult(response)

    def stream_infer(
        self,
        inputs_iterator,
        stream_timeout=None,
        headers=None,
        compression_algorithm=None,
    ):
        """Map an async iterator of request kwargs dicts onto the bidi stream.

        Yields (InferResult, error) tuples (parity: reference aio
        stream_infer).  Each item from *inputs_iterator* is a dict of
        ``infer``-style kwargs.
        """

        async def _requests():
            async for kwargs in inputs_iterator:
                yield build_infer_request(
                    kwargs["model_name"],
                    kwargs["inputs"],
                    kwargs.get("model_version", ""),
                    kwargs.get("outputs"),
                    kwargs.get("request_id", ""),
                    kwargs.get("sequence_id", 0),
                    kwargs.get("sequence_start", False),
                    kwargs.get("sequence_end", False),
                    kwargs.get("priority", 0),
                    kwargs.get("timeout"),
                    kwargs.get("parameters"),
                )

        async def _responses():
            try:
                stream = self._stubs["ModelStreamInfer"](
                    _requests(),
                    metadata=_metadata(_stamp_tenant(headers, self._tenant)),
                    timeout=stream_timeout,
                    compression=_grpc_compression(compression_algorithm),
                )
                async for response in stream:
                    error = (
                        InferenceServerException(response.error_message)
                        if response.error_message
                        else None
                    )
                    yield InferResult(response.infer_response), error
            except grpc.RpcError as e:
                raise_error_grpc(e)

        return _responses()
