"""Synchronous gRPC client for KServe-v2 servers (Triton-compatible).

Capability parity with ``tritonclient.grpc`` (reference
src/python/library/tritonclient/grpc/__init__.py): every GRPCInferenceService
RPC including bidirectional streaming inference (``start_stream`` /
``async_stream_infer`` over an ``_InferStream``), SSL and keepalive channel
configuration, per-call metadata/timeout/compression, plus the client_tpu
TpuSharedMemory* extension verbs. Stubs are built over grpc's generic channel
API from client_tpu._grpc_service (no grpcio-tools codegen).
"""

import queue
import re
import threading

import grpc

from client_tpu import resilience as _resilience
from client_tpu import tracing as _tracing
from client_tpu._grpc_infer import (  # noqa: F401  (re-exported API surface)
    InferResult,
    build_infer_request,
    set_infer_parameter,
)
from client_tpu._grpc_service import build_stubs
from client_tpu._infer_types import InferInput, InferRequestedOutput  # noqa: F401
from client_tpu._proto import inference_pb2 as pb
from client_tpu._proto import model_config_pb2  # noqa: F401
from client_tpu.utils import (
    SERVER_NOT_READY,
    SERVER_READY,
    SERVER_UNREACHABLE,
    InferenceServerException,
    raise_error,
    stamp_tenant as _stamp_tenant,
)

__all__ = [
    "InferenceServerClient",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "KeepAliveOptions",
]

# Never limit message size client-side (parity: reference common.h:54,
# MAX_GRPC_MESSAGE_SIZE = INT32_MAX).
MAX_GRPC_MESSAGE_SIZE = 2**31 - 1

# INT32_MAX sentinel the reference uses for "not set" keepalive values.
INT32_MAX = 2**31 - 1


class KeepAliveOptions:
    """gRPC keepalive channel arguments (parity: reference grpc/__init__.py:139)."""

    def __init__(
        self,
        keepalive_time_ms=INT32_MAX,
        keepalive_timeout_ms=20000,
        keepalive_permit_without_calls=False,
        http2_max_pings_without_data=2,
    ):
        self.keepalive_time_ms = keepalive_time_ms
        self.keepalive_timeout_ms = keepalive_timeout_ms
        self.keepalive_permit_without_calls = keepalive_permit_without_calls
        self.http2_max_pings_without_data = http2_max_pings_without_data


def raise_error_grpc(rpc_error):
    exc = InferenceServerException(
        msg=rpc_error.details(),
        status=str(rpc_error.code().name),
        debug_details=rpc_error,
    )
    # server backoff hint (the gRPC spelling of HTTP Retry-After): QoS and
    # overload sheds attach it as trailing metadata; the retry policy's
    # delay_for() honors exc.retry_after_s
    try:
        for key, value in rpc_error.trailing_metadata() or ():
            if key == "retry-after":
                exc.retry_after_s = float(value)
                break
    except Exception:
        pass  # a malformed hint must never mask the real error
    raise exc from None


def build_trace_setting_request(model_name, settings):
    """TraceSettingRequest from a plain dict (shared by the sync and aio
    clients — the builders are pure functions of ``settings``)."""
    request = pb.TraceSettingRequest(model_name=model_name)
    for key, value in (settings or {}).items():
        if value is None:
            request.settings[key]  # present-but-empty clears the setting
        elif isinstance(value, (list, tuple)):
            request.settings[key].value.extend(str(v) for v in value)
        else:
            request.settings[key].value.append(str(value))
    return request


def build_log_settings_request(settings):
    """LogSettingsRequest from a plain dict (shared sync/aio)."""
    request = pb.LogSettingsRequest()
    for key, value in settings.items():
        if value is None:
            request.settings[key]
        elif isinstance(value, bool):  # before int: bool is an int subclass
            request.settings[key].bool_param = value
        elif isinstance(value, int):
            request.settings[key].uint32_param = value
        else:
            request.settings[key].string_param = str(value)
    return request


def _stream_error(error_message):
    """ModelStreamInferResponse.error_message -> exception.  The server
    encodes any status code as a leading "[<status>] " prefix (the wire type
    has no status field); strip it back out."""
    m = re.match(r"\[([A-Za-z0-9_]+)\] (.*)", error_message, re.DOTALL)
    if m:
        return InferenceServerException(m.group(2), status=m.group(1))
    return InferenceServerException(error_message)


def _channel_options(keepalive_options=None, channel_args=None):
    options = [
        ("grpc.max_send_message_length", MAX_GRPC_MESSAGE_SIZE),
        ("grpc.max_receive_message_length", MAX_GRPC_MESSAGE_SIZE),
        ("grpc.primary_user_agent", "client_tpu"),
    ]
    ka = keepalive_options or KeepAliveOptions()
    options += [
        ("grpc.keepalive_time_ms", ka.keepalive_time_ms),
        ("grpc.keepalive_timeout_ms", ka.keepalive_timeout_ms),
        (
            "grpc.keepalive_permit_without_calls",
            int(ka.keepalive_permit_without_calls),
        ),
        ("grpc.http2.max_pings_without_data", ka.http2_max_pings_without_data),
    ]
    if channel_args:
        options += list(channel_args)
    return options


def _metadata(headers):
    return tuple((k.lower(), str(v)) for k, v in (headers or {}).items())


def _attempt_timeout(client_timeout, deadline_remaining_s):
    """Per-attempt RPC timeout: the caller's client_timeout capped by the
    retry deadline's remaining budget (shared by the sync and aio clients)."""
    if deadline_remaining_s is None:
        return client_timeout
    if client_timeout is None:
        return max(deadline_remaining_s, 1e-3)
    return max(min(client_timeout, deadline_remaining_s), 1e-3)


class _InferStream:
    """One bidirectional ModelStreamInfer stream.

    Requests are pushed into a queue consumed by a generator the RPC reads;
    responses are pulled by a handler thread that invokes the user callback
    (parity: reference _InferStream/_RequestIterator grpc/__init__.py:2155-2305).
    """

    _CLOSE = object()

    def __init__(self, callback, stubs, metadata, stream_timeout, compression):
        self._callback = callback
        self._request_queue = queue.SimpleQueue()
        self._active = True
        self._lock = threading.Lock()
        self._response_iterator = stubs["ModelStreamInfer"](
            iter(self._request_queue.get, self._CLOSE),
            metadata=metadata,
            timeout=stream_timeout,
            compression=compression,
        )
        self._handler = threading.Thread(
            target=self._process_responses, name="client_tpu-grpc-stream", daemon=True
        )
        self._handler.start()

    def send(self, request):
        with self._lock:
            if not self._active:
                raise_error("stream is closed")
            self._request_queue.put(request)

    def close(self, cancel_requests=False):
        with self._lock:
            if not self._active:
                return
            self._active = False
        if cancel_requests:
            self._response_iterator.cancel()
        self._request_queue.put(self._CLOSE)
        self._handler.join(timeout=30)

    def _process_responses(self):
        try:
            for response in self._response_iterator:
                error = (
                    _stream_error(response.error_message)
                    if response.error_message
                    else None
                )
                result = InferResult(response.infer_response)
                self._callback(result=result, error=error)
        except grpc.RpcError as e:
            if e.code() not in (grpc.StatusCode.CANCELLED,):
                self._callback(
                    result=None,
                    error=InferenceServerException(
                        msg=e.details(), status=str(e.code().name), debug_details=e
                    ),
                )
        with self._lock:
            self._active = False


class InferenceServerClient:
    """Blocking gRPC client for every GRPCInferenceService RPC.

    Parity: reference grpc/__init__.py:181-1782.
    """

    def __init__(
        self,
        url,
        verbose=False,
        ssl=False,
        root_certificates=None,
        private_key=None,
        certificate_chain=None,
        creds=None,
        keepalive_options=None,
        channel_args=None,
        retry_policy=None,
        tracer=None,
        tenant=None,
    ):
        options = _channel_options(keepalive_options, channel_args)
        if creds is not None:
            self._channel = grpc.secure_channel(url, creds, options=options)
        elif ssl:
            rc = pk = cc = None
            if root_certificates:
                with open(root_certificates, "rb") as f:
                    rc = f.read()
            if private_key:
                with open(private_key, "rb") as f:
                    pk = f.read()
            if certificate_chain:
                with open(certificate_chain, "rb") as f:
                    cc = f.read()
            credentials = grpc.ssl_channel_credentials(rc, pk, cc)
            self._channel = grpc.secure_channel(url, credentials, options=options)
        else:
            self._channel = grpc.insecure_channel(url, options=options)
        self._stubs = build_stubs(self._channel)
        self._endpoint = url  # host:port identity (trace attempt spans)
        self._verbose = verbose
        self._stream = None
        # Opt-in resilience for unary RPCs (client_tpu.resilience.RetryPolicy);
        # None keeps the original single-attempt behavior.  Streaming RPCs
        # are never retried (replay would re-send every queued request).
        self._retry_policy = retry_policy
        # Opt-in tracing (client_tpu.tracing.ClientTracer): client spans +
        # traceparent propagation over gRPC metadata.
        self._tracer = tracer
        # Tenant identity stamped as x-tenant-id metadata on EVERY verb,
        # unary and streaming (an explicitly passed header wins).
        self._tenant = None if tenant is None else str(tenant)

    # -- lifecycle -----------------------------------------------------------

    def close(self):
        self.stop_stream()
        self._channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _call(self, name, request, headers=None, client_timeout=None,
              trace=None, **kwargs):
        if self._retry_policy is None:
            return self._attempt_once(
                name, request, headers, client_timeout, trace, **kwargs
            )

        def attempt(timeout_s):
            timeout = _attempt_timeout(client_timeout, timeout_s)
            return self._attempt_once(
                name, request, headers, timeout, trace, **kwargs
            )

        return _resilience.call_with_retry(attempt, self._retry_policy)

    def _attempt_once(self, name, request, headers, client_timeout, trace,
                      **kwargs):
        """One RPC attempt in a trace attempt span — retries show as
        repeated ATTEMPT_START/ATTEMPT_END pairs."""
        with _tracing.attempt_span(trace, endpoint=self._endpoint):
            return self._call_once(
                name, request, headers, client_timeout, **kwargs
            )

    def _call_once(self, name, request, headers=None, client_timeout=None, **kwargs):
        headers = _stamp_tenant(headers, self._tenant)
        if self._verbose:
            print(f"{name}, metadata {headers}\n{request}")
        try:
            response = self._stubs[name](
                request,
                metadata=_metadata(headers),
                timeout=client_timeout,
                **kwargs,
            )
            if self._verbose:
                print(response)
            return response
        except grpc.RpcError as e:
            raise_error_grpc(e)

    @staticmethod
    def _maybe_json(response, as_json):
        if not as_json:
            return response
        from google.protobuf import json_format

        return json_format.MessageToDict(response, preserving_proto_field_name=True)

    # -- health --------------------------------------------------------------
    # Health verbs answer False on transport errors instead of raising
    # (tritonclient reference semantics): probes must be safe to poll
    # against a down server.  They bypass the retry policy (_call_once) —
    # an unavailable answer IS the probe result, not a failure to retry.

    def is_server_live(self, headers=None, client_timeout=None):
        try:
            return self._call_once(
                "ServerLive", pb.ServerLiveRequest(), headers, client_timeout
            ).live
        except InferenceServerException:
            return False

    def is_server_ready(self, headers=None, client_timeout=None):
        try:
            return self._call_once(
                "ServerReady", pb.ServerReadyRequest(), headers, client_timeout
            ).ready
        except InferenceServerException:
            return False

    def server_state(self, headers=None, client_timeout=None):
        """READY / NOT_READY / UNREACHABLE (client_tpu.utils constants).

        A draining server ANSWERS the ServerReady RPC with ready=False
        (NOT_READY); a dead one fails the RPC itself (UNREACHABLE) — the
        distinction a replica set routes on."""
        try:
            r = self._call_once(
                "ServerReady", pb.ServerReadyRequest(), headers, client_timeout
            )
        except InferenceServerException:
            return SERVER_UNREACHABLE
        return SERVER_READY if r.ready else SERVER_NOT_READY

    def is_model_ready(
        self, model_name, model_version="", headers=None, client_timeout=None
    ):
        request = pb.ModelReadyRequest(name=model_name, version=model_version)
        try:
            return self._call_once(
                "ModelReady", request, headers, client_timeout
            ).ready
        except InferenceServerException:
            return False

    # -- metadata / config ---------------------------------------------------

    def get_server_metadata(self, headers=None, as_json=False, client_timeout=None):
        response = self._call(
            "ServerMetadata", pb.ServerMetadataRequest(), headers, client_timeout
        )
        return self._maybe_json(response, as_json)

    def get_model_metadata(
        self,
        model_name,
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        request = pb.ModelMetadataRequest(name=model_name, version=model_version)
        return self._maybe_json(
            self._call("ModelMetadata", request, headers, client_timeout), as_json
        )

    def get_model_config(
        self,
        model_name,
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        request = pb.ModelConfigRequest(name=model_name, version=model_version)
        return self._maybe_json(
            self._call("ModelConfig", request, headers, client_timeout), as_json
        )

    # -- repository ----------------------------------------------------------

    def get_model_repository_index(
        self, headers=None, as_json=False, client_timeout=None
    ):
        return self._maybe_json(
            self._call(
                "RepositoryIndex", pb.RepositoryIndexRequest(), headers, client_timeout
            ),
            as_json,
        )

    def load_model(
        self,
        model_name,
        headers=None,
        config=None,
        files=None,
        client_timeout=None,
    ):
        request = pb.RepositoryModelLoadRequest(model_name=model_name)
        if config is not None:
            request.parameters["config"].string_param = (
                config if isinstance(config, str) else __import__("json").dumps(config)
            )
        for path, content in (files or {}).items():
            request.parameters[path].bytes_param = content
        self._call("RepositoryModelLoad", request, headers, client_timeout)

    def unload_model(
        self, model_name, headers=None, unload_dependents=False, client_timeout=None
    ):
        request = pb.RepositoryModelUnloadRequest(model_name=model_name)
        request.parameters["unload_dependents"].bool_param = unload_dependents
        self._call("RepositoryModelUnload", request, headers, client_timeout)

    # -- statistics / trace / log --------------------------------------------

    def get_inference_statistics(
        self,
        model_name="",
        model_version="",
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        request = pb.ModelStatisticsRequest(name=model_name, version=model_version)
        return self._maybe_json(
            self._call("ModelStatistics", request, headers, client_timeout), as_json
        )

    def update_trace_settings(
        self,
        model_name="",
        settings=None,
        headers=None,
        as_json=False,
        client_timeout=None,
    ):
        request = build_trace_setting_request(model_name, settings)
        return self._maybe_json(
            self._call("TraceSetting", request, headers, client_timeout), as_json
        )

    def get_trace_settings(
        self, model_name="", headers=None, as_json=False, client_timeout=None
    ):
        request = pb.TraceSettingRequest(model_name=model_name)
        return self._maybe_json(
            self._call("TraceSetting", request, headers, client_timeout), as_json
        )

    def update_log_settings(
        self, settings, headers=None, as_json=False, client_timeout=None
    ):
        request = build_log_settings_request(settings)
        return self._maybe_json(
            self._call("LogSettings", request, headers, client_timeout), as_json
        )

    def get_log_settings(self, headers=None, as_json=False, client_timeout=None):
        return self._maybe_json(
            self._call("LogSettings", pb.LogSettingsRequest(), headers, client_timeout),
            as_json,
        )

    # -- shared memory -------------------------------------------------------

    def get_system_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        request = pb.SystemSharedMemoryStatusRequest(name=region_name)
        return self._maybe_json(
            self._call("SystemSharedMemoryStatus", request, headers, client_timeout),
            as_json,
        )

    def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, client_timeout=None
    ):
        request = pb.SystemSharedMemoryRegisterRequest(
            name=name, key=key, offset=offset, byte_size=byte_size
        )
        self._call("SystemSharedMemoryRegister", request, headers, client_timeout)

    def unregister_system_shared_memory(
        self, name="", headers=None, client_timeout=None
    ):
        request = pb.SystemSharedMemoryUnregisterRequest(name=name)
        self._call("SystemSharedMemoryUnregister", request, headers, client_timeout)

    def get_cuda_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        request = pb.CudaSharedMemoryStatusRequest(name=region_name)
        return self._maybe_json(
            self._call("CudaSharedMemoryStatus", request, headers, client_timeout),
            as_json,
        )

    def register_cuda_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, client_timeout=None
    ):
        request = pb.CudaSharedMemoryRegisterRequest(
            name=name,
            raw_handle=raw_handle,
            device_id=device_id,
            byte_size=byte_size,
        )
        self._call("CudaSharedMemoryRegister", request, headers, client_timeout)

    def unregister_cuda_shared_memory(self, name="", headers=None, client_timeout=None):
        request = pb.CudaSharedMemoryUnregisterRequest(name=name)
        self._call("CudaSharedMemoryUnregister", request, headers, client_timeout)

    def get_tpu_shared_memory_status(
        self, region_name="", headers=None, as_json=False, client_timeout=None
    ):
        request = pb.TpuSharedMemoryStatusRequest(name=region_name)
        return self._maybe_json(
            self._call("TpuSharedMemoryStatus", request, headers, client_timeout),
            as_json,
        )

    def register_tpu_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None, client_timeout=None
    ):
        """Register a TPU device-buffer region (client_tpu extension RPC)."""
        request = pb.TpuSharedMemoryRegisterRequest(
            name=name,
            raw_handle=raw_handle,
            device_id=device_id,
            byte_size=byte_size,
        )
        self._call("TpuSharedMemoryRegister", request, headers, client_timeout)

    def unregister_tpu_shared_memory(self, name="", headers=None, client_timeout=None):
        request = pb.TpuSharedMemoryUnregisterRequest(name=name)
        self._call("TpuSharedMemoryUnregister", request, headers, client_timeout)

    # -- inference -----------------------------------------------------------

    def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        client_timeout=None,
        headers=None,
        compression_algorithm=None,
        parameters=None,
    ):
        with _tracing.client_span(self._tracer, model_name) as trace:
            request = build_infer_request(
                model_name,
                inputs,
                model_version,
                outputs,
                request_id,
                sequence_id,
                sequence_start,
                sequence_end,
                priority,
                timeout,
                parameters,
            )
            if trace is not None:
                trace.event("CLIENT_SERIALIZE_END")
                headers = dict(headers or {})
                headers["traceparent"] = trace.traceparent()
            response = self._call(
                "ModelInfer",
                request,
                headers,
                client_timeout,
                trace=trace,
                compression=_grpc_compression(compression_algorithm),
            )
            return InferResult(response)

    def async_infer(
        self,
        model_name,
        inputs,
        callback,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        client_timeout=None,
        headers=None,
        compression_algorithm=None,
        parameters=None,
    ):
        """Fire-and-callback inference: ``callback(result, error)`` runs on the
        gRPC completion thread (parity: reference grpc/__init__.py:1471)."""
        request = build_infer_request(
            model_name,
            inputs,
            model_version,
            outputs,
            request_id,
            sequence_id,
            sequence_start,
            sequence_end,
            priority,
            timeout,
            parameters,
        )
        try:
            future = self._stubs["ModelInfer"].future(
                request,
                metadata=_metadata(_stamp_tenant(headers, self._tenant)),
                timeout=client_timeout,
                compression=_grpc_compression(compression_algorithm),
            )
        except grpc.RpcError as e:
            raise_error_grpc(e)

        def _done(f):
            try:
                callback(result=InferResult(f.result()), error=None)
            except grpc.RpcError as e:
                callback(
                    result=None,
                    error=InferenceServerException(
                        msg=e.details(), status=str(e.code().name), debug_details=e
                    ),
                )

        future.add_done_callback(_done)
        return future

    # -- streaming -----------------------------------------------------------

    def start_stream(
        self,
        callback,
        stream_timeout=None,
        headers=None,
        compression_algorithm=None,
    ):
        """Open the bidirectional inference stream; ``callback(result, error)``
        fires per response (parity: reference grpc/__init__.py:1615)."""
        if self._stream is not None:
            raise_error("cannot start another stream with one already active")
        self._stream = _InferStream(
            callback,
            self._stubs,
            _metadata(_stamp_tenant(headers, self._tenant)),
            stream_timeout,
            _grpc_compression(compression_algorithm),
        )

    def stop_stream(self, cancel_requests=False):
        if self._stream is not None:
            self._stream.close(cancel_requests)
            self._stream = None

    def async_stream_infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        enable_empty_final_response=False,
        priority=0,
        timeout=None,
        parameters=None,
    ):
        """Enqueue one request on the active stream (parity: reference
        grpc/__init__.py:1681)."""
        if self._stream is None:
            raise_error("stream not available, call start_stream() first")
        request = build_infer_request(
            model_name,
            inputs,
            model_version,
            outputs,
            request_id,
            sequence_id,
            sequence_start,
            sequence_end,
            priority,
            timeout,
            parameters,
        )
        if enable_empty_final_response:
            request.parameters["triton_enable_empty_final_response"].bool_param = True
        self._stream.send(request)


def _grpc_compression(algorithm):
    if algorithm is None:
        return None
    name = str(algorithm).lower()
    if name == "deflate":
        return grpc.Compression.Deflate
    if name == "gzip":
        return grpc.Compression.Gzip
    if name in ("none", ""):
        return grpc.Compression.NoCompression
    raise_error(f"unsupported compression algorithm '{algorithm}' (gzip/deflate/none)")
