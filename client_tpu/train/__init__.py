"""Training checkpoint / resume for the model families (orbax-backed).

The serving stack already has the reference's checkpoint-reuse semantics
(InferInput.Reset, sequence-id reuse — SURVEY §5.4); this module adds the
framework-scale counterpart the reference never needed: durable training
state.  A CheckpointManager wraps orbax with the two properties multi-chip
training needs:

- **sharding-aware restore**: pass the live (possibly mesh-sharded) state
  as ``template`` and each leaf is restored onto its donor's sharding —
  params land back on the dp/tp/sp/ep/pp mesh with no host-side gather.
- **atomic, retention-managed steps**: orbax writes to a temp dir and
  renames, so a killed run never sees a torn checkpoint; ``max_to_keep``
  bounds disk.

Works on any backend (tests run it on the CPU mesh); async save is off by
default to keep the API synchronous and deterministic.
"""

import jax
import orbax.checkpoint as ocp


class CheckpointManager:
    """Save/restore (params, opt_state, step) training state.

    Usage::

        mgr = CheckpointManager(dir, max_to_keep=3)
        mgr.save(step, params=params, opt_state=opt_state)
        ...
        restored = mgr.restore(template={"params": params,
                                         "opt_state": opt_state})
        params, opt_state = restored["params"], restored["opt_state"]
        start = mgr.latest_step() + 1
    """

    def __init__(self, directory, max_to_keep=3):
        import os

        self._dir = os.path.abspath(str(directory))
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=False,
            ),
        )

    def save(self, step, /, **state):
        """Write one atomic checkpoint for ``step`` (kwargs form the tree)."""
        self._mgr.save(step, args=ocp.args.StandardSave(dict(state)))
        self._mgr.wait_until_finished()

    def latest_step(self):
        """Newest retained step, or None if the directory holds none."""
        return self._mgr.latest_step()

    def restore(self, template, step=None):
        """Restore ``step`` (default: latest) shaped/sharded like template.

        Every leaf comes back with the template leaf's dtype and sharding —
        a mesh-sharded template restores straight onto the mesh.
        """
        if step is None:
            step = self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self._dir}")
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype,
                sharding=getattr(x, "sharding", None),
            ),
            template,
        )
        return self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )

    def all_steps(self):
        return list(self._mgr.all_steps())

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
