"""client_tpu — a TPU-native client framework for KServe-v2 inference servers.

Capability surface mirrors the Triton client libraries (reference:
/root/reference/src/python/library/tritonclient) with the CUDA shared-memory
transport replaced by a libtpu/XLA-PJRT device-buffer path:

- ``client_tpu.http`` / ``client_tpu.http.aio``  — HTTP/REST clients
- ``client_tpu.grpc`` / ``client_tpu.grpc.aio``  — gRPC clients (incl. streaming)
- ``client_tpu.utils``                           — dtypes + (de)serialization
- ``client_tpu.utils.shared_memory``             — POSIX system shared memory
- ``client_tpu.utils.tpu_shared_memory``         — TPU HBM device-buffer regions
- ``client_tpu.serve``                           — in-process KServe-v2 server with a
  JAX/TPU execution runtime (hermetic test double *and* a real TPU serving path)
- ``client_tpu.balance``                         — client-side replica set: health/circuit-
  aware load balancing + failover across server replicas
- ``client_tpu.perf``                            — perf_analyzer-class load generator
"""

from client_tpu._version import __version__

__all__ = ["__version__"]
