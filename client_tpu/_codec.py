"""KServe-v2 HTTP body codec: JSON header + appended raw binary tensors.

Shared by the HTTP client and the in-process server so both sides of the
binary-tensor-data extension (`Inference-Header-Content-Length`) are encoded and
decoded by one implementation. Spec shape matches the reference client's
request builder (tritonclient/http/__init__.py:82-139) and result parser
(http/__init__.py:2045-2115).
"""

import gzip
import json
import zlib

from client_tpu.utils import InferenceServerException


def build_infer_request_body(
    inputs,
    outputs=None,
    request_id="",
    sequence_id=0,
    sequence_start=False,
    sequence_end=False,
    priority=0,
    timeout=None,
    parameters=None,
):
    """Render InferInput/InferRequestedOutput lists into (body, json_size).

    ``json_size`` is None when no raw binary section follows the JSON header
    (pure-JSON request).
    """
    infer_request = {}
    if request_id:
        infer_request["id"] = request_id
    params = {}
    if sequence_id:
        params["sequence_id"] = sequence_id
        params["sequence_start"] = bool(sequence_start)
        params["sequence_end"] = bool(sequence_end)
    if priority:
        params["priority"] = priority
    if timeout is not None:
        params["timeout"] = timeout
    if parameters:
        params.update(parameters)
    if params:
        infer_request["parameters"] = params

    binary_blobs = []
    inputs_json = []
    for inp in inputs:
        entry = {
            "name": inp.name(),
            "shape": inp.shape(),
            "datatype": inp.datatype(),
        }
        if inp.parameters():
            entry["parameters"] = dict(inp.parameters())
        raw = inp.raw_data()
        if raw is not None:
            binary_blobs.append(raw)
        elif inp.nonbinary_data() is not None:
            entry["data"] = inp.nonbinary_data()
        elif "shared_memory_region" not in inp.parameters():
            raise InferenceServerException(
                f"input '{inp.name()}' has no data; call set_data_from_numpy "
                "or set_shared_memory"
            )
        inputs_json.append(entry)
    infer_request["inputs"] = inputs_json

    if outputs:
        outputs_json = []
        for out in outputs:
            entry = {"name": out.name()}
            if out.parameters():
                entry["parameters"] = dict(out.parameters())
            outputs_json.append(entry)
        infer_request["outputs"] = outputs_json
    else:
        # No explicit outputs: ask for all outputs as binary (binary-data-output
        # request parameter from the spec's binary-data extension).
        infer_request.setdefault("parameters", {})["binary_data_output"] = True

    header = json.dumps(infer_request).encode("utf-8")
    if binary_blobs:
        return b"".join([header] + binary_blobs), len(header)
    return header, None


def parse_infer_request_body(body, header_length=None):
    """Server side: split request body into (header_dict, binary_section)."""
    if header_length is None:
        return json.loads(body.decode("utf-8")), b""
    header = json.loads(bytes(body[:header_length]).decode("utf-8"))
    return header, body[header_length:]


def build_infer_response_body(response_json, binary_blobs):
    """Server side: render response header + binary outputs -> (body, json_size)."""
    header = json.dumps(response_json).encode("utf-8")
    if binary_blobs:
        return b"".join([header] + binary_blobs), len(header)
    return header, None


def parse_infer_response_body(body, header_length=None):
    """Client side: split response into (header_dict, binary_section)."""
    if header_length is None:
        return json.loads(body.decode("utf-8")), b""
    header_length = int(header_length)  # callers may pass the raw HTTP header
    header = json.loads(bytes(body[:header_length]).decode("utf-8"))
    return header, body[header_length:]


def compress(body, algorithm):
    """Compress a request body per Content-Encoding *algorithm* (gzip/deflate)."""
    if algorithm is None:
        return body
    if algorithm == "gzip":
        return gzip.compress(body)
    if algorithm == "deflate":
        return zlib.compress(body)
    raise InferenceServerException(
        f"unsupported compression algorithm '{algorithm}' (use gzip or deflate)"
    )


def decompress(body, content_encoding):
    """Decompress a body per its Content-Encoding header value."""
    if not content_encoding:
        return body
    enc = content_encoding.lower()
    if enc == "gzip":
        return gzip.decompress(body)
    if enc == "deflate":
        return zlib.decompress(body)
    if enc == "identity":
        return body
    raise InferenceServerException(f"unsupported Content-Encoding '{enc}'")
